"""Hierarchical KV tier (ISSUE 10): a host-RAM page tier under the
paged allocator, swap-in preemption resume, and a standing prefix store.

The PR 2–9 stack treats HBM as the ONLY KV tier: ``PoolExhausted``
means evict-and-replay — a preempted victim pays a replay prefill
proportional to its resident tokens (the ``O(replay)`` cost PERF_NOTES
documents), and a prefix-trie chain evicted under pool pressure is
simply recomputed on its next admission. This module adds the tier
below HBM, the same device↔host discipline the training side proves out
in the ZeRO-3 offload path (tests/test_offload.py):

- :class:`HostPageStore` — a host-numpy page pool: entries hold
  raw-uint8 page payloads + dtype/shape metadata (the
  :meth:`~paddle_tpu.serving.PagedKVCache.export_request` byte
  convention, so bf16 and every int8-KV tier round-trip exactly),
  LRU-bounded by a page capacity, with an optional STANDING on-disk
  layer (one ``.npz`` per prefix chain) that survives process restarts.

- :class:`TieredKVCache` — a :class:`~paddle_tpu.serving.PagedKVCache`
  whose evictions move bytes instead of dropping them:

  * **swap-out / swap-in** — a preemption victim's live pages gather to
    host (:func:`_pool_gather`, one jitted read) and its device pages
    free; resume allocates fresh pages and scatters the bytes back
    through the SHARED donated
    :func:`~paddle_tpu.serving.paged_cache._pool_scatter` program —
    the PR 9 handoff scatter, so swap-in is bit-identical to having
    never been evicted by the same argument the prefill→decode handoff
    gate already proves (raw bytes in, raw bytes out; page ids differ
    but the block table makes content position-addressed). Resume cost
    drops from ``O(resident tokens)`` of replay-prefill FLOPs to one
    host→device page copy.
  * **demote / promote** — a prefix-trie chain evicted under
    ``PoolExhausted`` demotes its full-page KV bytes to the host store
    (keyed by the chain's token prefix — the same context hash the trie
    uses) instead of dying; the next admission that walks past the
    device trie's span promotes matching host pages back into the pool
    and re-registers them, so the prompt prefix-HITs instead of
    re-prefilling.
  * **standing prefix store** — registered prompt chains write through
    to the store (RAM, plus disk when ``prefix_store_dir`` is set), so
    a RESTARTED engine — or a PR 9 cluster's replacement replica —
    serves a persisted system prompt as a prefix HIT without any drain
    checkpoint having been taken: the PR 8 drain/restore trie
    persistence generalized into an always-warm tier.

Fault sites (ISSUE 8 discipline): ``swap_out`` fires BEFORE any gather
(a fault commits nothing — the victim still evicts through the plain
path or the supervisor recovers it), ``swap_in`` BEFORE any allocation
(the payload survives for the retry). Both are chaos-soaked with zero
lost/duplicated requests (tools/chaos_soak.py).

Telemetry: the ``serving_swap_*`` family (out/in counters + bytes,
transfer-latency histograms), the ``serving_host_pool_*`` occupancy
gauges and the demote/promote counters — linted by
tools/check_instrumentation.py like every serving hot path.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..observability import hooks as _obs
from .paged_cache import PagedKVCache, PoolExhausted
from .resilience import (CorruptionDetected, _np_dtype, fault_point,
                         payload_checksums, tamper_point,
                         verify_checksums)


def _pool_gather(pool: Dict, src):
    """The swap-out gather program: read the pages at ids ``src`` out
    of every pool array — shape ``(L, k, page, ...)`` per array — as
    ONE jitted program (the read half of the
    :func:`~paddle_tpu.serving.paged_cache._pool_scatter` pair).
    Mosaic-lowered by ``tools/aot_validate.py --config serving-host``
    at fp, int8-KV and tp-sharded pool layouts."""
    return {name: arr[:, src] for name, arr in pool.items()}


def _key_name(key: bytes) -> str:
    """Stable on-disk name for a prefix-chain key (the chain's token
    bytes) — content-addressed, so two engines sharing one store
    directory converge on the same files."""
    return hashlib.sha1(key).hexdigest() + ".npz"


def _tampered_entry(entry: Dict) -> Dict:
    """A copy of ``entry`` with one payload byte flipped — the
    injector's payload-corruption mode (ISSUE 13:
    ``FaultInjector.arm_tamper``): the CHECKSUM verifier, not the
    injector, must detect the damage, so the whole
    detect→quarantine→replay path runs on real corrupt bytes."""
    arrays = {n: np.array(a, copy=True)
              for n, a in entry["arrays"].items()}
    name = sorted(arrays)[0]
    flat = arrays[name].reshape(-1).view(np.uint8)
    if flat.size:
        flat[flat.size // 2] ^= 0xFF
    out = dict(entry)
    out["arrays"] = arrays
    return out


class HostPageStore:
    """Host-numpy page pool: the RAM (+ optional disk) tier below HBM.

    Entries are keyed by an arbitrary hashable key — the tiered cache
    uses ``("swap", rid)`` for swapped-out requests, the raw token
    bytes of a chain prefix for demoted/persisted trie pages, and the
    adapter plane (ISSUE 14) ``b"adapter/<id>"`` for LoRA factors
    demoted on slot reclaim (:class:`~paddle_tpu.serving.adapters.
    AdapterPool`) — and hold
    raw-uint8 array payloads with dtype/shape metadata (the
    ``export_request`` byte convention: extension dtypes like bf16
    round-trip exactly). ``capacity_pages`` LRU-bounds RAM residency;
    dropping an entry is always safe (a dropped swap payload falls back
    to the replay-prefill resume, a dropped prefix page to a plain
    prefill miss). ``path`` adds the STANDING tier: entries put with
    ``persist=True`` (prefix chains) also land on disk as one ``.npz``
    each and are readable by any later process — a RAM miss falls
    through to disk before reporting a miss."""

    def __init__(self, page_size: int,
                 capacity_pages: Optional[int] = None,
                 path: Optional[str] = None,
                 max_disk_bytes: Optional[int] = None):
        if capacity_pages is not None and capacity_pages < 1:
            raise ValueError(
                f"HostPageStore: capacity_pages={capacity_pages} "
                f"must be >= 1 (or None for unbounded)")
        if max_disk_bytes is not None and max_disk_bytes < 1:
            raise ValueError(
                f"HostPageStore: max_disk_bytes={max_disk_bytes} "
                f"must be >= 1 (or None for unbounded)")
        self.page_size = page_size
        self.capacity_pages = capacity_pages
        self.path = path
        #: ISSUE 15 satellite: byte bound on the STANDING disk layer —
        #: long-running engines write prefix chains through forever,
        #: so without a cap artifacts/ grows without limit. Oldest-
        #: mtime files prune first (LRU by last write/promotion);
        #: pruning a standing entry is always safe — the next miss is
        #: a plain prefix MISS and the chain re-prefills.
        self.max_disk_bytes = max_disk_bytes
        self._entries: "OrderedDict" = OrderedDict()
        self.pages_resident = 0
        self.bytes_resident = 0
        self.puts_total = 0
        self.hits_total = 0
        self.misses_total = 0
        self.capacity_drops_total = 0
        #: corrupt/torn entries removed so they can never be re-served
        #: (ISSUE 13) — the integrity gate's quarantine counter
        self.quarantined_total = 0
        #: standing-store files (and bytes) removed by the disk bound —
        #: next to the corrupt-unlink counter, so dashboards can tell
        #: capacity pruning from quarantine
        self.disk_pruned_total = 0
        self.disk_pruned_bytes_total = 0
        # cached standing-store residency: adjusted on every write,
        # re-synced from a full directory scan only when the bound
        # trips (the prune needs the listing anyway to pick LRU) — a
        # put() on the serving hot path must not stat the whole
        # directory (engines sharing a dir drift the cache slightly;
        # the overflow re-scan corrects it before anything prunes)
        self._disk_bytes: Optional[int] = None
        if path is not None:
            os.makedirs(path, exist_ok=True)
            if max_disk_bytes is not None:
                self._disk_bytes = sum(
                    os.path.getsize(os.path.join(path, f))
                    for f in os.listdir(path) if f.endswith(".npz"))

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return self.contains(key)

    def contains(self, key) -> bool:
        """Side-effect-free existence probe: RAM membership plus a
        disk ``stat`` for bytes keys — no payload read, no LRU bump,
        no hit/miss counting, and crucially no disk→RAM promotion (a
        probe must never evict resident swap payloads to answer a
        yes/no question)."""
        if key in self._entries:
            return True
        return (self.path is not None and isinstance(key, bytes)
                and os.path.exists(
                    os.path.join(self.path, _key_name(key))))

    @staticmethod
    def encode(arrays: Dict[str, np.ndarray]) -> Dict:
        """Pack host arrays into the raw-uint8 + meta payload form,
        stamped with per-array CRCs (ISSUE 13) — every consumer
        verifies them before installing the bytes anywhere."""
        enc, meta, pages = {}, {}, 0
        for name, a in arrays.items():
            a = np.ascontiguousarray(a)
            enc[name] = np.frombuffer(a.tobytes(), np.uint8)
            meta[name] = {"shape": list(a.shape), "dtype": str(a.dtype)}
            if a.ndim >= 2:
                pages = max(pages, int(a.shape[1]))
        return {"arrays": enc, "meta": meta, "pages": pages,
                "bytes": sum(int(v.nbytes) for v in enc.values()),
                "checksums": payload_checksums(enc)}

    @staticmethod
    def decode(entry: Dict) -> Dict[str, np.ndarray]:
        """Unpack a payload back into typed host arrays."""
        return {
            name: np.frombuffer(bytes(entry["arrays"][name]),
                                _np_dtype(m["dtype"])).reshape(m["shape"])
            for name, m in entry["meta"].items()}

    def _account(self, entry: Dict, sign: int):
        self.pages_resident += sign * entry["pages"]
        self.bytes_resident += sign * entry["bytes"]

    def _publish(self):
        _obs.serving_host_pool(self.pages_resident, self.bytes_resident,
                               self.capacity_pages)

    def put(self, key, arrays: Dict[str, np.ndarray],
            extra: Optional[Dict] = None, persist: bool = False) -> Dict:
        """Store ``arrays`` (typed host arrays) under ``key``; returns
        the encoded entry. ``persist=True`` (bytes keys only — prefix
        chains) also writes the standing ``.npz`` when the store has a
        disk path. Over-capacity RAM entries drop LRU-first; persisted
        entries stay readable from disk after a RAM drop."""
        if persist and not isinstance(key, bytes):
            # validate BEFORE any mutation: the error path must leave
            # residency accounting and the gauges untouched
            raise ValueError(
                "HostPageStore: only bytes keys (prefix-chain token "
                "bytes) persist to the standing store")
        entry = self.encode(arrays)
        entry["extra"] = dict(extra or {})
        entry["persist"] = bool(persist)
        old = self._entries.pop(key, None)
        if old is not None:
            self._account(old, -1)
        self._entries[key] = entry
        self._account(entry, +1)
        self.puts_total += 1
        self._enforce_capacity()
        if persist and self.path is not None:
            self._write_disk(key, entry)
        self._publish()
        return entry

    def _enforce_capacity(self):
        """Drop LRU entries until RAM residency fits ``capacity_pages``
        — shared by :meth:`put` and :meth:`get`'s disk→RAM promotion,
        so read-driven residency obeys the same bound write-driven
        residency does (persisted entries stay readable from disk)."""
        if self.capacity_pages is None:
            return
        while (self.pages_resident > self.capacity_pages
               and len(self._entries) > 1):
            _, dropped = self._entries.popitem(last=False)
            self._account(dropped, -1)
            self.capacity_drops_total += 1

    def _write_disk(self, key: bytes, entry: Dict):
        meta = {"meta": entry["meta"], "pages": entry["pages"],
                "extra": entry["extra"],
                "checksums": entry.get("checksums")}
        fn = os.path.join(self.path, _key_name(key))
        tmp = fn + ".tmp"
        old_size = 0
        if self._disk_bytes is not None:
            try:
                old_size = os.path.getsize(fn)
            except OSError:
                pass
        with open(tmp, "wb") as f:
            np.savez(f, key=np.frombuffer(key, np.uint8),
                     meta=np.frombuffer(json.dumps(meta).encode(),
                                        np.uint8),
                     **{f"a_{n}": a for n, a in entry["arrays"].items()})
        os.replace(tmp, fn)     # atomic: a reader never sees half a file
        if self._disk_bytes is not None:
            try:
                self._disk_bytes += os.path.getsize(fn) - old_size
            except OSError:
                pass
            if self._disk_bytes > self.max_disk_bytes:
                self._enforce_disk_bound(keep=fn)

    def _enforce_disk_bound(self, keep: Optional[str] = None) -> int:
        """Prune oldest-mtime standing-store files until total disk
        residency fits ``max_disk_bytes`` (ISSUE 15 satellite). Runs
        only when the cached byte total trips the bound; the full
        directory scan here re-syncs that cache (the listing is needed
        anyway to pick the LRU victims). The just-written file
        (``keep``) never prunes — the bound must not eat the entry
        whose write triggered it. Best-effort: a file raced away by
        another engine sharing the directory just skips."""
        if self.max_disk_bytes is None or self.path is None:
            return 0
        try:
            files = []
            total = 0
            for fn in os.listdir(self.path):
                if not fn.endswith(".npz"):
                    continue
                full = os.path.join(self.path, fn)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                files.append((st.st_mtime, st.st_size, full))
                total += st.st_size
            pruned = 0
            for _mtime, size, full in sorted(files):
                if total <= self.max_disk_bytes:
                    break
                if full == keep:
                    continue
                try:
                    os.unlink(full)
                except OSError:
                    continue
                total -= size
                pruned += 1
                self.disk_pruned_total += 1
                self.disk_pruned_bytes_total += size
            self._disk_bytes = total
            if pruned:
                _obs.serving_host_disk_pruned(
                    pruned, self.disk_pruned_bytes_total)
            return pruned
        except OSError:
            return 0

    def _quarantine_disk(self, fn: str):
        """Remove a corrupt/torn standing-store file so it is NEVER
        re-read (counted; removal failure still counts — the in-RAM
        miss already protects this process, the unlink protects the
        next one)."""
        self.quarantined_total += 1
        _obs.serving_integrity("disk_store", "quarantined")
        self._unlink_tracked(fn)

    def _unlink_tracked(self, fn: str) -> None:
        """Unlink a standing-store file, keeping the cached disk-byte
        total honest (best-effort on both syscalls)."""
        size = 0
        if self._disk_bytes is not None:
            try:
                size = os.path.getsize(fn)
            except OSError:
                pass
        try:
            os.unlink(fn)
        except OSError:
            return
        if self._disk_bytes is not None:
            self._disk_bytes = max(0, self._disk_bytes - size)

    def _read_disk(self, key: bytes) -> Optional[Dict]:
        fn = os.path.join(self.path, _key_name(key))
        if not os.path.exists(fn):
            return None
        try:
            with np.load(fn) as data:
                meta = json.loads(bytes(data["meta"]).decode())
                entry = {"arrays": {n[2:]: np.asarray(data[n])
                                    for n in data.files
                                    if n.startswith("a_")},
                         "meta": meta["meta"], "pages": meta["pages"],
                         "extra": meta["extra"], "persist": True,
                         "checksums": meta.get("checksums")}
        except Exception:
            # torn/truncated/foreign file: a detected corruption — the
            # file quarantines (never re-read) and the caller serves a
            # plain miss (prefix MISS -> replay), not a crash
            _obs.serving_integrity("disk_store", "detected")
            self._quarantine_disk(fn)
            return None
        try:
            # bit-flips np.load cannot see: verify the stamped CRCs
            # BEFORE the entry enters RAM or any scatter (ISSUE 13)
            verify_checksums(entry["arrays"], entry.get("checksums"),
                             "disk_store")
        except CorruptionDetected:
            _obs.serving_integrity("disk_store", "detected")
            self._quarantine_disk(fn)
            return None
        entry["bytes"] = sum(int(v.nbytes)
                             for v in entry["arrays"].values())
        try:
            # bump mtime on promotion so the disk bound's oldest-mtime
            # pruning is genuinely LRU (last write OR promotion), not
            # FIFO by original write time — without this the hottest
            # standing entries would prune first
            os.utime(fn, None)
        except OSError:
            pass
        return entry

    def get(self, key, touch: bool = True) -> Optional[Dict]:
        """RAM lookup, falling through to the standing disk tier for
        bytes keys; a disk hit re-enters RAM (promote within the host
        hierarchy). ``touch`` bumps LRU recency."""
        entry = self._entries.get(key)
        if entry is None and self.path is not None \
                and isinstance(key, bytes):
            entry = self._read_disk(key)
            if entry is not None:
                self._entries[key] = entry
                self._account(entry, +1)
                self._enforce_capacity()
                self._publish()
        if entry is None:
            if touch:
                self.misses_total += 1
            return None
        if touch:
            self.hits_total += 1
            self._entries.move_to_end(key)
        return entry

    def pop(self, key) -> Optional[Dict]:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self._account(entry, -1)
            self._publish()
        return entry

    def quarantine(self, key, site: str) -> None:
        """Remove a corrupt entry EVERYWHERE it could be re-served
        (RAM and, for persisted bytes keys, the standing disk file) and
        count it (ISSUE 13). A quarantined entry is gone for good: the
        next lookup is an honest miss, and its request recovers through
        the gated replay path."""
        self.pop(key)
        self.quarantined_total += 1
        _obs.serving_integrity(site, "quarantined")
        if self.path is not None and isinstance(key, bytes):
            self._unlink_tracked(
                os.path.join(self.path, _key_name(key)))

    def stats(self) -> Dict:
        return {"entries": len(self._entries),
                "pages_resident": self.pages_resident,
                "bytes_resident": self.bytes_resident,
                "capacity_pages": self.capacity_pages,
                "puts_total": self.puts_total,
                "hits_total": self.hits_total,
                "misses_total": self.misses_total,
                "capacity_drops_total": self.capacity_drops_total,
                "quarantined_total": self.quarantined_total,
                "disk_pruned_total": self.disk_pruned_total,
                "disk_pruned_bytes_total": self.disk_pruned_bytes_total}


class TieredKVCache(PagedKVCache):
    """A :class:`~paddle_tpu.serving.PagedKVCache` with the host tier
    under its allocator (ISSUE 10): preemption victims SWAP OUT to a
    :class:`HostPageStore` and resume by swap-in scatter instead of
    replay-prefill; prefix-trie chains evicted under pool pressure
    DEMOTE to host and PROMOTE back on the next matching admission; and
    registered prompt chains write through to a standing store
    (``prefix_store_dir``) that survives engine restarts.

    Every host entry travels as raw bytes + dtype/shape meta (the PR 9
    handoff convention) and re-enters the pool through the SHARED
    donated ``_pool_scatter`` program — so swap-in and promotion are
    bit-identical to never having left HBM, at fp and int8-KV and on
    tp-sharded pools (gated in tests/test_host_tier.py).

    ``store`` shares one :class:`HostPageStore` across caches (the
    PR 9 cluster attaches one store to every replica, so rehomed
    sessions swap in on their NEW replica and a replacement replica
    warms from the standing prefix tier). All host bookkeeping here is
    host-side numpy; the only device programs are the one gather and
    the shared scatter."""

    def __init__(self, cfg, max_batch: int, max_len: int, *,
                 host_capacity_pages: Optional[int] = None,
                 prefix_store_dir: Optional[str] = None,
                 persist_prefix: bool = True,
                 store: Optional[HostPageStore] = None,
                 swap_in_retries: int = 2,
                 retry_sleep=time.sleep, **kw):
        super().__init__(cfg, max_batch, max_len, **kw)
        self.host = store if store is not None else HostPageStore(
            self.page_size, capacity_pages=host_capacity_pages,
            path=prefix_store_dir)
        self.persist_prefix = persist_prefix
        self._gather_fn = None
        # bounded idempotent retry of the swap-in scatter (ISSUE 13):
        # a transient fault retries in place with exponential backoff
        # instead of costing a full engine recovery — every failed
        # attempt frees what it allocated first, and the fault site
        # fires before any commit, so retries never double-install
        self.swap_in_retries = int(swap_in_retries)
        self._retry_sleep = retry_sleep
        self.swap_outs_total = 0
        self.swap_ins_total = 0
        self.swap_out_bytes_total = 0
        self.swap_in_bytes_total = 0
        self.swap_in_pages_total = 0
        self.swap_replay_fallbacks = 0
        self.swap_in_retries_total = 0
        #: why the LAST swap_in fell back to replay-prefill
        #: ("dropped" | "stale" | "corrupt"; None after a success) —
        #: the predictor's trace mark reads this for the request trace
        self.last_swap_fallback: Optional[str] = None
        self.corruptions_detected_total = 0
        self.demotions_total = 0
        self.promote_hits_total = 0
        self._swap_charge = 0   # pending planner debit, tokens
        # async swap-outs issued but not yet fenced into the host
        # store (ISSUE 12): key -> {arrays (device), length, pages,
        # t0}. The gather program was enqueued and its device→host
        # copies started non-blocking; fence_swaps() materializes the
        # entries. Everything that READS the store (has_swapped /
        # swap_in / drop_swapped) fences first, so a pending payload
        # is never invisible.
        self._pending_swaps: "OrderedDict" = OrderedDict()
        #: last swap-in wall latencies (ms), host-side — the bench
        #: rider's swap_in_ms_p50 source (bounded; metrics registry
        #: keeps the full histogram)
        self.swap_in_ms: List[float] = []

    # ---- shared device programs ----
    def _gather_device(self, ids) -> Dict:
        """Launch the jitted gather (:func:`_pool_gather`) for the
        pages at ``ids`` and return the DEVICE arrays without fetching
        — the async swap-out path starts their device→host copies
        non-blocking and fences later. PJRT usage holds keep the read
        ordered before any later donation of the same pool buffers, so
        freeing the pages (host bookkeeping) immediately after is
        safe."""
        import jax
        import jax.numpy as jnp
        if self._gather_fn is None:
            self._gather_fn = jax.jit(_pool_gather)
        return self._gather_fn(self.pool,
                               jnp.asarray(np.asarray(ids, np.int32)))

    def _gather_pages(self, ids) -> Dict[str, np.ndarray]:
        """Fetch the pages at ``ids`` from every pool array to host as
        typed numpy — one jitted gather (:func:`_pool_gather`) + one
        device→host transfer, shared across all swap/demote paths and
        carried across supervisor rebuilds like the scatter/CoW
        programs."""
        return {n: np.asarray(a)
                for n, a in self._gather_device(ids).items()}

    def _decode_validated(self, entry: Dict, k: Optional[int] = None,
                          site: str = "host_payload") -> Dict:
        """Decode a host payload and validate it against THIS pool's
        geometry (array set, dtypes, layer/page shape) — a stale
        standing store from a different config must read as a loud
        error on the swap path and a silent miss on the prefix path,
        never a corrupt scatter. The payload's stamped CRCs verify
        FIRST (ISSUE 13): corrupt bytes raise
        :class:`~paddle_tpu.serving.CorruptionDetected` before any
        decode — the callers quarantine and fall back to replay."""
        verify_checksums(entry["arrays"], entry.get("checksums"), site)
        if set(entry["meta"]) != set(self.pool):
            raise ValueError(
                f"host payload arrays {sorted(entry['meta'])} != pool "
                f"arrays {sorted(self.pool)} — kv-dtype tier mismatch")
        arrays = self.decode_entry(entry)
        for name, a in arrays.items():
            want = self.pool[name]
            if str(a.dtype) != str(want.dtype):
                raise ValueError(
                    f"host payload {name} dtype {a.dtype} != pool "
                    f"dtype {want.dtype}")
            got = tuple(a.shape)
            kk = got[1] if k is None else k
            if (got[0] != want.shape[0] or got[1] != kk
                    or got[2:] != tuple(want.shape[2:])):
                raise ValueError(
                    f"host payload {name} shape {got} does not match "
                    f"pool page shape "
                    f"{(want.shape[0], kk) + tuple(want.shape[2:])}")
        return arrays

    @staticmethod
    def decode_entry(entry: Dict) -> Dict[str, np.ndarray]:
        return HostPageStore.decode(entry)

    # ---- swap-out / swap-in (preemption tier) ----
    @staticmethod
    def _swap_key(rid: int):
        return ("swap", int(rid))

    def swap_out(self, slot: int, rid: int,
                 nonblocking: bool = False) -> int:
        """Preemption SWAP-OUT: gather ``slot``'s live pages (the ones
        covering ``lengths[slot]`` committed tokens — the tail
        reservation holds no KV) to the host store keyed by ``rid``,
        then release the device pages exactly as
        :meth:`~paddle_tpu.serving.PagedKVCache.evict_for_preempt`
        would. Returns pages actually freed. The fault site fires
        BEFORE the gather, so an injected fault commits nothing and
        the supervisor's recovery sees an ordinary running slot.

        ``nonblocking=True`` (the overlapped runtime, ISSUE 12): the
        gather is enqueued and its device→host copies START here, but
        the host-store entry materializes at the next
        :meth:`fence_swaps` — issued under the in-flight decode step,
        fenced at commit, so the DMA never sits on the critical path.
        Every store read (has_swapped / swap_in) fences first, so the
        payload is observable the moment anyone asks."""
        if not self.active[slot]:
            raise ValueError(f"swap_out of inactive slot {slot}")
        length = int(self.lengths[slot])
        if length <= 0:
            raise ValueError(
                f"swap_out of slot {slot} with no committed tokens — "
                f"mid-prefill victims evict and replay instead")
        fault_point("swap_out")
        t0 = time.perf_counter_ns()
        k = self.pages_for(length)
        ids = self._slot_pages[slot][:k]
        if nonblocking:
            out = self._gather_device(ids)
            for a in out.values():
                start = getattr(a, "copy_to_host_async", None)
                if start is not None:
                    start()             # non-blocking device→host DMA
            self._pending_swaps[self._swap_key(rid)] = {
                "arrays": out, "length": length, "pages": k, "t0": t0}
            return self.evict_for_preempt(slot)
        arrays = self._gather_pages(ids)
        entry = self.host.put(self._swap_key(rid), arrays,
                              extra={"length": length})
        freed = self.evict_for_preempt(slot)
        self.swap_outs_total += 1
        self.swap_out_bytes_total += entry["bytes"]
        _obs.serving_swap_out(t0, entry["bytes"], k)
        return freed

    def fence_swaps(self) -> int:
        """Materialize every pending async swap-out into the host
        store (the commit-time fence of the overlapped runtime).
        Returns the number fenced; 0 when nothing was pending. The
        ``serving_swap_out`` latency histogram spans issue→fence —
        the honest wall cost of the overlapped DMA."""
        if not self._pending_swaps:
            return 0
        n = 0
        pend, self._pending_swaps = self._pending_swaps, OrderedDict()
        for key, ent in pend.items():
            arrays = {nm: np.asarray(a)
                      for nm, a in ent["arrays"].items()}
            entry = self.host.put(key, arrays,
                                  extra={"length": ent["length"]})
            self.swap_outs_total += 1
            self.swap_out_bytes_total += entry["bytes"]
            _obs.serving_swap_out(ent["t0"], entry["bytes"],
                                  ent["pages"])
            n += 1
        return n

    def has_swapped(self, rid: int) -> bool:
        key = self._swap_key(rid)
        return key in self._pending_swaps or self.host.contains(key)

    def drop_swapped(self, rid: int) -> None:
        """Retire a swapped payload (its request finished or was
        cancelled while evicted) — always safe, never required: a
        missing payload just means the resume replays."""
        self._pending_swaps.pop(self._swap_key(rid), None)
        self.host.pop(self._swap_key(rid))

    def _quarantine_swap_in(self, rid: int) -> None:
        """Corrupt swap payload: quarantine (counted, never re-served)
        and count the fall-back to the gated replay resume — the
        journal holds everything needed to recompute the KV bit-exactly.
        ``fence_swaps`` already drained any pending async copy of this
        payload into the store, so quarantining the store entry is the
        whole cleanup."""
        self.corruptions_detected_total += 1
        _obs.serving_integrity("swap_in", "detected")
        self.host.quarantine(self._swap_key(rid), "swap_in")
        self.swap_replay_fallbacks += 1
        _obs.serving_swap_fallback()
        _obs.serving_integrity("swap_in", "replayed")

    def swap_in(self, slot: int, rid: int, total_tokens: int,
                expect_tokens: int) -> Optional[int]:
        """Preemption SWAP-IN: re-admit ``rid`` on ``slot`` by
        allocating its full ``total_tokens`` page budget and scattering
        the swapped payload's bytes into the leading pages (the shared
        donated ``_pool_scatter``). Returns the restored committed
        length, or None when no valid payload exists (LRU-dropped, or
        ``expect_tokens`` — the journal-authoritative resume length —
        no longer matches) and the caller must fall back to the
        replay-prefill resume. Raises
        :class:`~paddle_tpu.serving.PoolExhausted` with NOTHING
        committed (the payload survives for the retry)."""
        self.fence_swaps()      # a pending async payload must be visible
        entry = self.host.get(self._swap_key(rid))
        if entry is None:
            self.last_swap_fallback = "dropped"
            self.swap_replay_fallbacks += 1
            _obs.serving_swap_fallback()
            return None
        length = int(entry["extra"]["length"])
        if length != int(expect_tokens):
            # the journal rolled the request past/behind this payload
            # (shouldn't happen — tokens only append — but the journal
            # is authoritative): drop and replay rather than trust it
            self.last_swap_fallback = "stale"
            self.drop_swapped(rid)
            self.swap_replay_fallbacks += 1
            _obs.serving_swap_fallback()
            return None
        if tamper_point("swap_in"):
            # injected payload corruption: real bytes flip, the CRC
            # verifier below must catch them (never the injector)
            entry = _tampered_entry(entry)
        t0 = time.perf_counter_ns()
        n = self._check_admit(slot, total_tokens)
        k = self.pages_for(length)
        try:
            arrays = self._decode_validated(entry, k=k, site="swap_in")
        except CorruptionDetected:
            self.last_swap_fallback = "corrupt"
            self._quarantine_swap_in(rid)
            return None
        # bounded idempotent retry (ISSUE 13): a transient fault at the
        # site — or inside the alloc/scatter — retries in place with
        # exponential backoff instead of poisoning the whole engine.
        # Each failed attempt frees everything it allocated before
        # re-raising (and the fault site fires before any allocation),
        # so a retried swap-in can never double-install pages.
        # PoolExhausted stays back-pressure (the caller's contract);
        # an injected corrupt-mode fault is a detection (quarantine +
        # replay, same as real corrupt bytes above).
        attempt = 0
        while True:
            try:
                fault_point("swap_in")
                pages = self._alloc_with_evict(n)
                try:
                    self._scatter_pages(arrays, pages[:k])
                except Exception:
                    self.allocator.free(pages)
                    raise
                break
            except PoolExhausted:
                raise
            except CorruptionDetected:
                self.last_swap_fallback = "corrupt"
                self._quarantine_swap_in(rid)
                return None
            except Exception:
                attempt += 1
                if attempt > self.swap_in_retries:
                    raise
                self.swap_in_retries_total += 1
                _obs.serving_integrity_retry("swap_in")
                self._retry_sleep(min(0.2, 0.005 * 2 ** (attempt - 1)))
        self._install(slot, pages)
        self.lengths[slot] = length
        self.last_swap_fallback = None
        self.host.pop(self._swap_key(rid))
        self.swap_ins_total += 1
        self.swap_in_pages_total += k
        self.swap_in_bytes_total += entry["bytes"]
        self._swap_charge += k * self.page_size
        self.swap_in_ms.append((time.perf_counter_ns() - t0) / 1e6)
        del self.swap_in_ms[:-1024]
        _obs.serving_swap_in(t0, entry["bytes"], k)
        return length

    def consume_swap_charge(self) -> int:
        """Token-equivalent debit of the swap-ins since the last call —
        ``page_size`` tokens per swapped-in page, the same rate a
        prefill chunk is charged (a swap-in writes the same KV bytes a
        chunk would, minus the FLOPs). The scheduler reserves this out
        of the step's token budget so the budget stays a hard bound on
        per-step HBM writes even when admissions swap in."""
        c = self._swap_charge
        self._swap_charge = 0
        return c

    # ---- prefix demote / promote / standing store ----
    def _chain_key(self, prompt: np.ndarray, n_pages: int) -> bytes:
        return np.ascontiguousarray(
            prompt[:n_pages * self.page_size]).tobytes()

    def register_prefix(self, slot: int, prompt):
        """Publish the prompt's pages to the trie (parent behavior)
        AND write each full page through to the standing host store —
        chains survive trie eviction (demote becomes a no-op re-keying)
        and engine restarts (the persistence half of ROADMAP item 4)."""
        super().register_prefix(slot, prompt)
        if self.prefix is None or not self.persist_prefix:
            return
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0 or not self.active[slot]:
            return
        pg = self.page_size
        nfull = prompt.size // pg
        missing = [j for j in range(nfull)
                   if not self.host.contains(
                       self._chain_key(prompt, j + 1))]
        if not missing:
            return
        pages = self._slot_pages[slot]
        gathered = self._gather_pages([pages[j] for j in missing])
        for i, j in enumerate(missing):
            self.host.put(
                self._chain_key(prompt, j + 1),
                {n: a[:, i:i + 1] for n, a in gathered.items()},
                extra={"tokens":
                       prompt[:(j + 1) * pg].tolist()},
                persist=True)

    def _evict_prefix(self, need: int) -> int:
        """Trie eviction under pool pressure, with DEMOTION: each full
        page dropped from the trie lands in the host store first (keyed
        by its chain prefix) unless already written through — so
        ``PoolExhausted`` moves cold prefix KV down the hierarchy
        instead of destroying it. Partial-page tails do not demote
        (their rows are donor state for copy-on-write, recomputed
        cheaply on the next miss)."""
        pend: List = []

        def demote(chain_tokens: np.ndarray, page: int):
            key = chain_tokens.tobytes()
            if not self.host.contains(key):
                pend.append((key, chain_tokens, page))
            self.demotions_total += 1
            _obs.serving_prefix_demoted(1)
        freed = self.prefix.evict(self.allocator, need, on_evict=demote)
        if pend:
            # ONE batched gather for the whole eviction batch (not one
            # dispatch per page on the PoolExhausted admission path).
            # Deferring past the free is safe: freeing is host
            # bookkeeping — the caller's re-allocation writes nothing
            # into these pages until after this returns.
            gathered = self._gather_pages([p for _, _, p in pend])
            for i, (key, toks, _page) in enumerate(pend):
                self.host.put(key,
                              {n: a[:, i:i + 1]
                               for n, a in gathered.items()},
                              extra={"tokens": toks.tolist()},
                              persist=self.persist_prefix)
        return freed

    def admit_prompt(self, slot: int, prompt, total_tokens: int):
        """Parent admission, preceded by PROMOTION: host-store chains
        extending past the device trie's matched span scatter back into
        freshly allocated pages and re-register, so the parent's trie
        match then covers them — a demoted (or persisted-from-a-past-
        process) system prompt is a prefix HIT, not a re-prefill."""
        if self.prefix is not None:
            self._promote_prefix(
                np.asarray(prompt, np.int32).reshape(-1))
        return super().admit_prompt(slot, prompt, total_tokens)

    def _promote_prefix(self, prompt: np.ndarray) -> int:
        pg = self.page_size
        max_full = max(0, (prompt.size - 1) // pg)
        if max_full == 0:
            return 0
        matched, _ = self.prefix.match(prompt)
        entries = []
        j = len(matched)
        while j < max_full:
            entry = self.host.get(self._chain_key(prompt, j + 1))
            if entry is None:
                break
            entries.append(entry)
            j += 1
        if not entries:
            return 0
        t0 = time.perf_counter_ns()
        try:
            arrays = [self._decode_validated(e, k=1,
                                             site="prefix_promote")
                      for e in entries]
        except CorruptionDetected:
            # corrupt demoted/persisted chain (bit-flip, torn write):
            # quarantine every entry of the chain (counted, never
            # re-served — RAM and disk) and serve the admission as a
            # plain prefix MISS; the replay prefill recomputes the KV
            self.corruptions_detected_total += 1
            _obs.serving_integrity("prefix_promote", "detected")
            for jj in range(len(matched), len(matched) + len(entries)):
                self.host.quarantine(self._chain_key(prompt, jj + 1),
                                     "prefix_promote")
            _obs.serving_integrity("prefix_promote", "replayed")
            return 0
        except ValueError:
            # stale store (different geometry/kv tier): drop the bad
            # chain and serve the admission as a plain miss
            for jj in range(len(matched), len(matched) + len(entries)):
                self.host.pop(self._chain_key(prompt, jj + 1))
            return 0
        # pin the matched span FIRST (the same guard admit_prompt
        # carries): the eviction our own allocation may trigger must
        # not recycle a matched page mid-promotion — re-registering
        # the extended chain onto a recycled id would alias two chain
        # nodes onto one physical page (silent prefix corruption)
        matched = list(matched)
        self.allocator.share(matched)
        try:
            fresh = self._alloc_with_evict(len(entries))
        except PoolExhausted:
            self.allocator.free(matched)
            return 0            # no room to promote: plain miss, no harm
        try:
            merged = {n: np.concatenate([a[n] for a in arrays], axis=1)
                      for n in arrays[0]}
            self._scatter_pages(merged, fresh)
            span = len(matched) + len(entries)
            self.prefix.register(prompt[:span * pg], matched + fresh,
                                 self.allocator)
        except Exception:
            self.allocator.free(matched + fresh)
            raise
        # the trie owns the pages now; drop the pins + bootstrap refs
        self.allocator.free(matched + fresh)
        self.promote_hits_total += len(entries)
        _obs.serving_prefix_promoted(t0, len(entries))
        return len(entries)

    # ---- supervisor / cluster integration ----
    def adopt_host_tier(self, old: "TieredKVCache") -> None:
        """Carry the host tier across an engine rebuild
        (:meth:`~paddle_tpu.serving.EngineSupervisor._build`): the
        store is HOST state committed only after successful gathers —
        it survives a poisoned device pool, which is exactly what lets
        recovery swap sessions in instead of replaying them. Lifetime
        counters and the compiled gather carry too (monotonic stats,
        pure function). Pending ASYNC swap-outs (ISSUE 12) fence into
        the store first — their gathers committed on device before the
        fault — and a fence that itself fails just drops the payloads:
        those resumes fall back to the gated replay path."""
        try:
            old.fence_swaps()
        except Exception:
            old._pending_swaps.clear()
        self.host = old.host
        self._gather_fn = old._gather_fn
        self.persist_prefix = old.persist_prefix
        for name in ("swap_outs_total", "swap_ins_total",
                     "swap_out_bytes_total", "swap_in_bytes_total",
                     "swap_in_pages_total", "swap_replay_fallbacks",
                     "swap_in_retries_total",
                     "corruptions_detected_total",
                     "demotions_total", "promote_hits_total"):
            setattr(self, name, getattr(old, name))
        self.swap_in_ms = old.swap_in_ms
        self.swap_in_retries = old.swap_in_retries
        self._retry_sleep = old._retry_sleep

    def tier_stats(self) -> Dict:
        s = {"swap_outs_total": self.swap_outs_total,
             "swap_outs_pending": len(self._pending_swaps),
             "swap_ins_total": self.swap_ins_total,
             "swap_out_bytes_total": self.swap_out_bytes_total,
             "swap_in_bytes_total": self.swap_in_bytes_total,
             "swap_replay_fallbacks": self.swap_replay_fallbacks,
             "swap_in_retries_total": self.swap_in_retries_total,
             "corruptions_detected_total":
                 self.corruptions_detected_total,
             "prefix_demotions_total": self.demotions_total,
             "prefix_promote_hits_total": self.promote_hits_total}
        s.update({f"host_{k}": v for k, v in self.host.stats().items()})
        return s
