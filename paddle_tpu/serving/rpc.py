"""Socket RPC control plane for the multi-process serving cluster
(ISSUE 19).

A minimal length-prefixed, CRC-framed request/reply protocol over TCP —
the WAL's ``MAGIC | payload_len | crc32 | payload`` frame discipline
(:mod:`paddle_tpu.serving.wal`) lifted onto a socket, with its own
magic. One frame is one message:

- payload = ``u32 header_len | JSON header | blob bytes...`` — the
  header carries ``id`` / ``kind`` (call, reply, error) / ``method`` /
  ``data`` (JSON-able args or result) / ``blobs`` (name, dtype, shape
  per binary attachment, in payload order) / optional ``trace`` (the
  controller's trace id, stitching request spans across the process
  boundary — ISSUE 16 tracer).
- binary attachments (KV-page exports, fabric entries) ride as raw
  bytes after the header — the raw-uint8 + per-array-CRC32 payload
  convention from ISSUE 9/13 was designed for exactly this hop and
  ships unencoded; the frame CRC covers header and blobs together.

Failure discipline (the ISSUE 13 machinery, applied to the wire):

- a torn frame (EOF mid-frame), a bit-flipped frame (CRC mismatch) or
  a bad magic NEVER install anything — the receiver counts the event
  and drops the connection; the peer reconnects.
- :class:`RpcClient` retries transport-level failures with the bounded
  exponential backoff idiom (``min(cap, base * 2**(attempt-1))``,
  injectable sleep), reconnecting between attempts. Retries are safe
  because :class:`RpcServer` keeps a bounded per-client dedupe cache
  of serialized replies: a retried call whose first attempt DID
  execute replays the cached reply instead of executing twice
  (exactly-once for submit/adopt/finish).
- retry exhaustion surfaces a structured :class:`ReplicaUnreachable`
  to the router — never a hang, never a silent drop; the cluster maps
  it to the ``replica_unreachable`` finish reason (vs ``engine_dead``,
  which means the remote supervisor's circuit breaker opened).
- remote application exceptions travel as typed error envelopes and
  are re-raised client-side as the real classes (``PoolExhausted``,
  ``CorruptionDetected``, ``StepStalled``, ``EngineDead``...), so the
  cluster's handoff/failover except-clauses work unchanged across the
  process boundary. Unmapped types raise :class:`RpcRemoteError`.

Fault sites (ISSUE 8 discipline, fire BEFORE any commit):
``rpc_send`` before a frame hits the socket, ``rpc_recv`` before a
received reply is decoded — an injected fault at either is handled as
a transport failure (drop connection, bounded retry), so chaos at the
RPC plane exercises the same reconnect/dedupe path a flaky network
does.

The transport is injectable (anything with ``send_frame`` /
``recv_frame`` / ``settimeout`` / ``close``) so the retry/dedupe/
error machinery is testable without sockets; ``socket.socketpair``
drives the deterministic torn/corrupt/half-closed gates.

Host-side only: no jax imports, no device syncs — this module is in
the tools/check_instrumentation.py sync-free set.
"""
from __future__ import annotations

import itertools
import json
import os
import socket
import struct
import threading
import time
import zlib
from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from ..observability import hooks as _obs
from .paged_cache import PoolExhausted
from .resilience import (
    CorruptionDetected, EngineDead, InjectedFault, StepStalled,
    fault_point,
)

#: the RPC frame magic — same ``magic|len|crc32`` header struct as the
#: WAL's ``PTWL`` frames, distinct magic so a WAL segment fed to a
#: socket (or vice versa) is rejected as corrupt instead of parsed
MAGIC = b"PTRC"
_HDR = struct.Struct("<4sII")
_U32 = struct.Struct("<I")

#: hard ceiling on one frame's payload — a corrupt length field must
#: not make the receiver try to allocate gigabytes
MAX_FRAME_BYTES = 1 << 30


class RpcError(RuntimeError):
    """Base transport-level failure (retryable: the client drops the
    connection, reconnects and retries under its bounded budget)."""


class RpcClosed(RpcError):
    """The peer closed the stream cleanly between frames."""


class RpcTornFrame(RpcError):
    """EOF mid-frame — the sender died (or half-closed the socket)
    partway through a write; the partial bytes are discarded."""


class RpcCorruptFrame(RpcError):
    """Frame failed validation (bad magic, oversized length or CRC
    mismatch) — detected before anything is decoded or installed."""


class RpcTimeout(RpcError):
    """One attempt exceeded its deadline waiting on the socket."""


class ReplicaUnreachable(RuntimeError):
    """The client's bounded retry budget is exhausted: the replica
    process is gone (or the network to it is). Structured — carries
    the replica ``label`` and the last transport error — so the router
    can fail over and finish orphaned sessions with the distinct
    ``replica_unreachable`` reason instead of ``engine_dead``."""

    def __init__(self, label: str, detail: str = ""):
        self.label = label
        super().__init__(
            f"replica {label!r} unreachable after bounded retries"
            + (f": {detail}" if detail else ""))


class RpcRemoteError(RuntimeError):
    """A remote exception type the envelope mapping does not know —
    re-raised with the remote type name and message preserved."""

    def __init__(self, etype: str, detail: str = ""):
        self.etype = etype
        super().__init__(f"remote {etype}: {detail}")


# ---------------------------------------------------------------------------
# message codec


def _json_default(o):
    """JSON fallback for the numpy scalars that ride inside otherwise
    plain dicts (load_stats snapshots, export metadata)."""
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON-able on the RPC wire: {type(o)!r}")


def encode_message(header: Dict,
                   blobs: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """One message -> one CRC-framed byte string. ``blobs`` ride as raw
    bytes after the JSON header; their (name, dtype, shape) manifest is
    folded into the header so the receiver can slice them back out."""
    blobs = blobs or {}
    arrs = {k: np.ascontiguousarray(v) for k, v in blobs.items()}
    header = dict(header)
    header["blobs"] = [{"name": k, "dtype": str(a.dtype),
                        "shape": list(a.shape)}
                       for k, a in arrs.items()]
    hb = json.dumps(header, separators=(",", ":"),
                    default=_json_default).encode("utf-8")
    payload = b"".join([_U32.pack(len(hb)), hb]
                       + [a.tobytes() for a in arrs.values()])
    return _HDR.pack(MAGIC, len(payload),
                     zlib.crc32(payload) & 0xFFFFFFFF) + payload


def decode_message(payload: bytes) -> Tuple[Dict, Dict[str, np.ndarray]]:
    """Inverse of :func:`encode_message` (the frame CRC has already
    been verified by the transport). Blob arrays are copied out of the
    frame buffer so callers own writable storage."""
    (hlen,) = _U32.unpack_from(payload, 0)
    header = json.loads(payload[_U32.size:_U32.size + hlen]
                        .decode("utf-8"))
    off = _U32.size + hlen
    blobs: Dict[str, np.ndarray] = {}
    for m in header.pop("blobs", []):
        dt = np.dtype(m["dtype"])
        count = int(np.prod(m["shape"], dtype=np.int64)) if m["shape"] \
            else 1
        arr = np.frombuffer(payload, dtype=dt, count=count,
                            offset=off).reshape(m["shape"]).copy()
        blobs[m["name"]] = arr
        off += count * dt.itemsize
    return header, blobs


# ---------------------------------------------------------------------------
# transports


class SocketTransport:
    """Blocking framed byte stream over a connected TCP socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    @classmethod
    def connect(cls, host: str, port: int,
                timeout: Optional[float] = 10.0) -> "SocketTransport":
        sock = socket.create_connection((host, int(port)),
                                        timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return cls(sock)

    def settimeout(self, seconds: Optional[float]) -> None:
        self.sock.settimeout(seconds)

    def send_frame(self, frame: bytes) -> None:
        self.sock.sendall(frame)

    def recv_frame(self) -> bytes:
        hdr = self._recv_exact(_HDR.size, frame_start=True)
        magic, length, crc = _HDR.unpack(hdr)
        if magic != MAGIC:
            raise RpcCorruptFrame(f"bad magic {magic!r}")
        if length > MAX_FRAME_BYTES:
            raise RpcCorruptFrame(f"frame length {length} exceeds "
                                  f"{MAX_FRAME_BYTES}")
        payload = self._recv_exact(length, frame_start=False)
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise RpcCorruptFrame("payload crc mismatch")
        return payload

    def _recv_exact(self, n: int, frame_start: bool) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            try:
                chunk = self.sock.recv(min(1 << 20, n - len(buf)))
            except socket.timeout as e:
                raise RpcTimeout(f"socket recv timed out "
                                 f"({len(buf)}/{n} bytes)") from e
            if not chunk:
                if frame_start and not buf:
                    raise RpcClosed("peer closed the stream")
                raise RpcTornFrame(
                    f"EOF mid-frame after {len(buf)}/{n} bytes")
            buf += chunk
        return bytes(buf)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass


_CLIENT_SEQ = itertools.count(1)


class RpcClient:
    """One logical connection to one RPC server, with bounded
    idempotent retry. ``connect`` is any zero-arg callable returning a
    transport — injectable for deterministic tests; :meth:`dial` wires
    the TCP default. Calls are serialized per client (the cluster's
    control plane is synchronous by design — determinism gate)."""

    def __init__(self, connect: Callable[[], object], *,
                 label: str = "replica", retries: int = 3,
                 timeout_s: Optional[float] = 60.0,
                 backoff_s: float = 0.005, max_backoff_s: float = 0.2,
                 sleep: Callable[[float], None] = time.sleep):
        self._connect = connect
        self.label = label
        self.retries = int(retries)
        self.timeout_s = timeout_s
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._sleep = sleep
        self._t = None
        self._lock = threading.Lock()
        # globally-unique call ids: the server's dedupe cache is keyed
        # by (client token, call id) so two clients never collide
        self._token = f"{os.getpid()}.{next(_CLIENT_SEQ)}"
        self._id = 0
        self.retries_total = 0
        self.timeouts_total = 0

    def call(self, method: str, data: Optional[Dict] = None,
             blobs: Optional[Dict[str, np.ndarray]] = None, *,
             trace: Optional[int] = None,
             timeout_s: Optional[float] = None,
             retries: Optional[int] = None) -> Tuple[Dict, Dict]:
        """One request/reply exchange. Returns ``(data, blobs)`` from
        the reply; raises the re-mapped remote exception on an error
        envelope, :class:`ReplicaUnreachable` on retry exhaustion."""
        with self._lock:
            return self._call(method, data, blobs, trace,
                              self.timeout_s if timeout_s is None
                              else timeout_s,
                              self.retries if retries is None
                              else int(retries))

    def _call(self, method, data, blobs, trace, timeout, retries):
        self._id += 1
        header = {"id": self._id, "client": self._token,
                  "kind": "call", "method": method,
                  "data": data if data is not None else {}}
        if trace is not None:
            header["trace"] = int(trace)
        frame = encode_message(header, blobs)
        last: Optional[BaseException] = None
        for attempt in range(retries + 1):
            if attempt:
                self.retries_total += 1
                _obs.serving_rpc_retry(method)
                self._sleep(min(self.max_backoff_s,
                                self.backoff_s * 2 ** (attempt - 1)))
            t0 = _obs.generate_begin()
            try:
                t = self._transport(timeout)
                fault_point("rpc_send")
                t.send_frame(frame)
                payload = t.recv_frame()
                fault_point("rpc_recv")
                reply, rblobs = decode_message(payload)
                if reply.get("id") != self._id:
                    raise RpcCorruptFrame(
                        f"reply id {reply.get('id')} != {self._id}")
                _obs.serving_rpc_call(method, t0, len(frame),
                                      len(payload))
            except RpcTimeout as e:
                self.timeouts_total += 1
                _obs.serving_rpc_timeout(method)
                self._drop()
                last = e
            except (RpcError, InjectedFault, OSError) as e:
                if isinstance(e, RpcCorruptFrame):
                    _obs.serving_rpc_corrupt("crc")
                elif isinstance(e, RpcTornFrame):
                    _obs.serving_rpc_corrupt("torn")
                self._drop()
                last = e
            else:
                # raised OUTSIDE the try: a remote application
                # exception (CorruptionDetected, PoolExhausted, ...)
                # must reach the caller's except-clauses, not the
                # transport-retry catch above (CorruptionDetected IS
                # an InjectedFault)
                if reply.get("kind") == "error":
                    raise remote_exception(reply)
                return reply.get("data"), rblobs
        raise ReplicaUnreachable(self.label, f"{method}: {last!r}")

    @classmethod
    def dial(cls, host: str, port: int, **kw) -> "RpcClient":
        return cls(lambda: SocketTransport.connect(host, port), **kw)

    def _transport(self, timeout):
        if self._t is None:
            self._t = self._connect()
        if timeout is not None and hasattr(self._t, "settimeout"):
            self._t.settimeout(timeout)
        return self._t

    def _drop(self) -> None:
        if self._t is not None:
            try:
                self._t.close()
            except Exception:  # noqa: BLE001 - close is best-effort
                pass
            self._t = None

    def close(self) -> None:
        with self._lock:
            self._drop()


# ---------------------------------------------------------------------------
# remote-exception envelopes


def encode_exception(e: BaseException) -> Dict:
    """Exception -> JSON-able error-envelope fields."""
    out = {"kind": "error", "etype": type(e).__name__,
           "detail": str(e)}
    if isinstance(e, CorruptionDetected):
        out["eargs"] = [e.site]
    elif isinstance(e, InjectedFault):
        out["eargs"] = [e.site, e.mode]
    return out


#: remote type name -> rebuild(args, detail). The mapped classes are
#: exactly the ones the cluster's handoff/failover paths discriminate
#: on; anything else becomes an RpcRemoteError
_EXC_TYPES = {
    "PoolExhausted": lambda a, d: PoolExhausted(d),
    "CorruptionDetected":
        lambda a, d: CorruptionDetected(a[0] if a else "rpc"),
    "InjectedFault":
        lambda a, d: InjectedFault(a[0] if a else "rpc",
                                   a[1] if len(a) > 1 else "raise"),
    "StepStalled": lambda a, d: StepStalled(0.0),
    "EngineDead": lambda a, d: EngineDead(d),
    "ValueError": lambda a, d: ValueError(d),
    "KeyError": lambda a, d: KeyError(d),
    "RuntimeError": lambda a, d: RuntimeError(d),
}


def remote_exception(reply: Dict) -> BaseException:
    """Error envelope -> the exception to raise client-side."""
    build = _EXC_TYPES.get(reply.get("etype", ""))
    if build is None:
        return RpcRemoteError(reply.get("etype", "?"),
                              reply.get("detail", ""))
    return build(reply.get("eargs", []), reply.get("detail", ""))


# ---------------------------------------------------------------------------
# server


class RpcServer:
    """Threaded TCP server dispatching framed calls to ``handler``'s
    ``rpc_<method>(data, blobs)`` methods (returning ``data`` or
    ``(data, blobs)``). Dispatch is serialized under one lock — a
    replica node is single-engine, so concurrency lives between
    processes, not within one. Corrupt/torn inbound frames are
    counted and drop the connection (the client reconnects and
    retries); replies to already-executed call ids replay from a
    bounded per-client dedupe cache, so a retried call never executes
    twice."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0,
                 dedupe: int = 64):
        self.handler = handler
        self._dedupe = int(dedupe)
        self._lock = threading.Lock()
        self._replies: "OrderedDict[Tuple[str, int], bytes]" = \
            OrderedDict()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.frames_served = 0
        self.corrupt_frames = 0
        self.deduped_replies = 0

    def start(self) -> "RpcServer":
        """Accept loop in a daemon thread (in-process servers: the
        fabric in tests, loopback nodes)."""
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True, name="rpc-accept")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Accept loop inline (worker-process main loop). Returns when
        :meth:`shutdown` closes the listener."""
        while not self._done.is_set():
            try:
                sock, _ = self._sock.accept()
            except OSError:
                break
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True, name="rpc-conn").start()

    def _serve_conn(self, sock: socket.socket) -> None:
        t = SocketTransport(sock)
        while not self._done.is_set():
            try:
                payload = t.recv_frame()
            except RpcClosed:
                break
            except (RpcTornFrame, RpcCorruptFrame) as e:
                self.corrupt_frames += 1
                _obs.serving_rpc_corrupt(
                    "torn" if isinstance(e, RpcTornFrame) else "crc")
                break
            except (RpcTimeout, OSError):
                break
            try:
                header, blobs = decode_message(payload)
            except Exception:  # noqa: BLE001 - undecodable after CRC
                self.corrupt_frames += 1
                _obs.serving_rpc_corrupt("crc")
                break
            try:
                t.send_frame(self._dispatch(header, blobs))
            except OSError:
                break
        t.close()

    def _dispatch(self, header: Dict, blobs: Dict) -> bytes:
        key = (str(header.get("client", "")), int(header.get("id", 0)))
        method = str(header.get("method", ""))
        with self._lock:
            cached = self._replies.get(key)
            if cached is not None:
                self.deduped_replies += 1
                return cached
            t0 = _obs.generate_begin()
            reply = {"id": key[1], "kind": "reply"}
            oblobs = None
            try:
                fn = getattr(self.handler, "rpc_" + method, None)
                if fn is None:
                    raise ValueError(f"no such RPC method {method!r}")
                out = fn(header.get("data") or {}, blobs)
                data, oblobs = out if isinstance(out, tuple) \
                    else (out, None)
                reply["data"] = data
            except BaseException as e:  # noqa: BLE001 - envelope relay
                reply.update(encode_exception(e))
            frame = encode_message(reply, oblobs)
            self.frames_served += 1
            _obs.serving_rpc_served(method, t0)
            self._replies[key] = frame
            while len(self._replies) > self._dedupe:
                self._replies.popitem(last=False)
            return frame

    def shutdown(self) -> None:
        self._done.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover - close is best-effort
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
