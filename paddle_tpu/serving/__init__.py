"""Serving subsystem: paged KV cache + continuous batching.

- :mod:`paddle_tpu.serving.paged_cache` — global page pools, per-request
  block tables, the host-side :class:`BlockAllocator` (alloc/free/defrag
  stats) and :class:`PagedKVCache` bundle.
- the paged attention op lives in
  :mod:`paddle_tpu.ops.pallas.paged_attention` (Pallas kernel + pure-lax
  fallback) and the continuous-batching engine in
  :mod:`paddle_tpu.inference.predictor`
  (:class:`~paddle_tpu.inference.ContinuousBatchingEngine`).
"""
from .paged_cache import (  # noqa: F401
    TRASH_PAGE, BlockAllocator, PagedKVCache, PoolExhausted, PrefixCache,
)
