"""Serving subsystem: paged KV cache + continuous batching + the
SLO-aware scheduler.

- :mod:`paddle_tpu.serving.paged_cache` — global page pools, per-request
  block tables, the host-side :class:`BlockAllocator` (refcounted pages,
  alloc/free/defrag stats), the :class:`PrefixCache` hash-trie and the
  :class:`PagedKVCache` bundle (incl. the ``evict_for_preempt`` API).
- :mod:`paddle_tpu.serving.policy` — :class:`Priority` classes,
  structured :class:`FinishReason`, the :class:`TokenBudgetPlanner`
  step packer and the :class:`PreemptionPolicy` victim selector.
- :mod:`paddle_tpu.serving.scheduler` — :class:`ServingScheduler`, the
  priority/deadline/preemption control plane over the engine.
- :mod:`paddle_tpu.serving.speculative` — :class:`NgramProposer`
  (model-free prompt-lookup drafting), :class:`Speculator` (per-row
  acceptance-rate EMA + adaptive draft length) and the greedy
  :func:`longest_accepted_prefix` acceptance rule for the engine's
  batched-verify ``spec_step``.
- :mod:`paddle_tpu.serving.resilience` — fault-tolerant serving:
  :class:`FaultInjector` (deterministic seeded fault injection at the
  named hot-path :data:`~paddle_tpu.serving.resilience.SITES`),
  :class:`EngineSupervisor` (write-ahead :class:`RequestJournal`,
  token-identical crash recovery via the resume replay, circuit
  breaker + degraded-mode ladder, drain/restore with prefix-trie
  persistence).
- :mod:`paddle_tpu.serving.host_tier` — the hierarchical KV tier
  (ISSUE 10): :class:`HostPageStore` (host-numpy page pool with an
  optional standing on-disk layer) and :class:`TieredKVCache`
  (preemption swap-out/swap-in under the allocator, prefix-trie
  demote/promote, write-through prefix persistence across restarts).
- :mod:`paddle_tpu.serving.cluster` / :mod:`paddle_tpu.serving.router`
  — the disaggregated serving tier (ISSUE 9): :class:`ServingCluster`
  (N supervised replicas, prefill→decode KV handoff over the page
  export/import APIs, failover and rolling drain/upgrade) routed by
  :class:`ClusterRouter` (prefix-affinity placement, load/SLO-aware
  dispatch, per-tenant fair share + :class:`TenantQuota` rate limits).
- :mod:`paddle_tpu.serving.traffic` — the trace-driven traffic harness
  (ISSUE 13): :func:`synth_trace` (seeded open-loop traces — tenant
  prefix families, bursty/diurnal arrivals, mixed priority/deadline/
  length), :class:`FakeClock`, and :func:`run_trace` →
  :class:`SLOReport` (p99 TTFT, per-token latency, deadline-met
  fraction, goodput-under-SLO). The cluster side adds
  :class:`~paddle_tpu.serving.router.AdmissionController`
  (deadline-infeasible submissions shed at the door) and
  :class:`~paddle_tpu.serving.cluster.ClusterAutoscaler` (hysteresis
  scale up/down through the ``retire_replica`` drain path).
- :mod:`paddle_tpu.serving.adapters` — the multi-tenant adapter plane
  (ISSUE 14): :class:`AdapterRegistry` (the tenant population's packed
  q/o LoRA factors), :class:`AdapterPool` (device-resident refcounted
  slots with LRU reclaim, host-tier demote/promote, rank-bucketed
  compile keys, tp column-sharded B factors) and the
  :func:`init_lora` / :func:`merge_lora` reference helpers — one
  engine serves thousands of fine-tuned variants with the base
  weights loaded once.
- :mod:`paddle_tpu.serving.constraints` — grammar/JSON-schema
  constrained decoding: :class:`TokenDFA` (+ the
  :func:`dfa_from_sequences` / :func:`dfa_from_regex` /
  :func:`json_schema_dfa` compilers) applied as per-row logit masks in
  the engine's sampling step, with :class:`ConstraintState` advancing
  at commit.
- sampled speculation (ISSUE 14) lives in
  :mod:`paddle_tpu.serving.speculative`:
  :func:`rejection_sample_tokens` lifts spec decode's greedy-only
  restriction with standard min(1, p/q) rejection sampling.
- :mod:`paddle_tpu.serving.wal` — the crash-durable journal plane
  (ISSUE 15): :class:`WriteAheadLog` (segmented CRC-framed on-disk
  log under the request journal, configurable fsync ladder,
  incremental checkpoints that compact the log without stopping
  admissions) and :func:`recover_state` (torn-tail truncation +
  checkpoint-plus-suffix replay) — the machinery behind
  :meth:`EngineSupervisor.recover_from_disk` /
  :meth:`ServingCluster.recover_from_disk` cold-restart recovery.
- :mod:`paddle_tpu.serving.rpc` / :mod:`paddle_tpu.serving.node` /
  :mod:`paddle_tpu.serving.fabric` /
  :mod:`paddle_tpu.serving.multiproc` — the multi-PROCESS serving
  cluster (ISSUE 19): a minimal length-prefixed CRC-framed socket RPC
  layer (:class:`RpcClient` / :class:`RpcServer` — torn/corrupt frames
  detected, bounded idempotent retry, typed remote exceptions),
  :class:`~paddle_tpu.serving.node.ReplicaNode` worker processes (one
  supervisor + scheduler each, per-replica WAL dir as durable process
  identity), the shared content-addressed KV fabric
  (:class:`FabricServer` / :class:`FabricClient` — the PR 10 standing
  prefix store as a cluster-wide service, CRC-verified promotes,
  quarantine-on-corrupt) and :class:`MultiProcessCluster` — the
  in-process cluster control plane re-hosted over RPC stubs,
  token-identical to :class:`ServingCluster` on the same trace,
  ``kill -9`` of a replica process handled as WAL-recovering failover.
- the paged attention op lives in
  :mod:`paddle_tpu.ops.pallas.paged_attention` (Pallas kernel + pure-lax
  fallback) and the continuous-batching engine in
  :mod:`paddle_tpu.inference.predictor`
  (:class:`~paddle_tpu.inference.ContinuousBatchingEngine`).
"""
from .paged_cache import (  # noqa: F401
    TRASH_PAGE, BlockAllocator, PagedKVCache, PoolExhausted, PrefixCache,
)
from .policy import (  # noqa: F401
    FinishReason, PreemptionPolicy, Priority, StepPlan,
    TokenBudgetPlanner,
)
from .resilience import (  # noqa: F401
    DEGRADED_MODES, SITES, CorruptionDetected, EngineDead,
    EngineSupervisor, FaultInjector, InjectedFault, RequestJournal,
    StepStalled, fault_point, load_drain_checkpoint,
)
from .scheduler import ServingScheduler  # noqa: F401
from .speculative import (  # noqa: F401
    NgramProposer, Speculator, TreeDraft, build_comb_tree,
    longest_accepted_path, longest_accepted_prefix,
    rejection_sample_tokens, tree_ancestor_matrix, tree_depths,
    tree_rejection_sample,
)
from .adapters import (  # noqa: F401
    AdapterPool, AdapterPoolExhausted, AdapterRegistry, init_lora,
    merge_lora,
)
from .constraints import (  # noqa: F401
    ConstraintState, TokenDFA, dfa_from_regex, dfa_from_sequences,
    json_schema_dfa,
)
from .host_tier import HostPageStore, TieredKVCache  # noqa: F401
from .wal import WriteAheadLog, recover_state  # noqa: F401
from .router import (  # noqa: F401
    AdmissionController, ClusterRouter, TenantQuota,
)
from .cluster import ClusterAutoscaler, ServingCluster  # noqa: F401
from .traffic import (  # noqa: F401
    FakeClock, SLOReport, TraceRequest, run_trace, synth_trace,
)
from .rpc import (  # noqa: F401
    ReplicaUnreachable, RpcClient, RpcClosed, RpcCorruptFrame,
    RpcError, RpcRemoteError, RpcServer, RpcTimeout, RpcTornFrame,
)
from .fabric import FabricClient, FabricServer  # noqa: F401
from .node import ReplicaNode, tiny_llama_engine  # noqa: F401
from .multiproc import (  # noqa: F401
    FabricProcess, MultiProcessCluster, ReplicaProcess,
)
