"""Replica worker process for the multi-process serving cluster
(ISSUE 19).

One :class:`ReplicaNode` process = one
:class:`~paddle_tpu.serving.EngineSupervisor` (engine + scheduler +
journal) behind an :class:`~paddle_tpu.serving.rpc.RpcServer`. The RPC
surface is the cluster control plane's EXISTING replica vocabulary —
``submit_request`` / ``step`` / ``load_stats`` / the handoff
export/adopt/finish triplet / ``drain`` — so
:class:`~paddle_tpu.serving.multiproc.MultiProcessCluster` re-hosts the
in-process :class:`~paddle_tpu.serving.cluster.ServingCluster` logic
over stubs without changing any of it.

Durable process identity (ISSUE 15): each node owns a per-replica WAL
directory. ``kill -9`` the process and start a replacement with
``recover: true`` on the same directory — it rebuilds through
:meth:`EngineSupervisor.recover_from_disk` (torn tail truncated,
checkpoint + log-suffix replay) and reports the recovered session
records in its hello, so the controller re-anchors its handles and the
replay continues token-identically.

Request state crosses the wire as the journal's OWN record shape
(:meth:`JournalEntry.as_record` / :func:`_session_from_record`): the
same records that make sessions durable on disk make them portable
between processes. Token updates ship as per-request APPEND deltas
(tokens only ever grow between journal syncs), so a step reply is a
few ints per live request, not the whole transcript.

The shared KV fabric (:mod:`paddle_tpu.serving.fabric`) attaches at
ENGINE-FACTORY level: the node dials a :class:`FabricClient` and
injects it as the tiered cache's host store, so every rebuild of the
engine — including post-crash recovery — is fabric-warm: prefix
chains another replica demoted PROMOTE here instead of cold
prefilling.

Run a worker with::

    python -m paddle_tpu.serving.node --spec /path/spec.json

where the spec file holds the JSON :func:`ReplicaNode` spec (engine
factory + knobs, WAL dir, fabric endpoint, trace/metrics flags,
``port_file`` handshake path).
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import hooks as _obs
from .fabric import FabricClient, entry_from_wire, entry_to_wire, \
    write_endpoint_file
from .resilience import EngineSupervisor, _session_from_record
from .rpc import RpcServer


# ---------------------------------------------------------------------------
# request records on the wire


def request_record(req, now: Optional[float] = None,
                   admitted: bool = False) -> Dict:
    """Controller-side record builder: the
    :meth:`~paddle_tpu.serving.resilience.JournalEntry.as_record`
    shape, produced from a bare request handle (the multi-process
    controller holds no engine, journal or clock epoch shared with the
    node — deadlines ship as REMAINING seconds for the same reason
    drain records do). ``admitted=True`` marks a rehomed in-flight
    session, which the node-side rebuild resumes with the preempted
    replay semantics."""
    remaining = None
    if req.deadline_at is not None and now is not None:
        remaining = float(req.deadline_at - now)
    eos = req.eos_token_id
    return {"rid": int(req.rid),
            "prompt": np.asarray(req.prompt).reshape(-1).tolist(),
            "max_new_tokens": int(req.max_new_tokens),
            "eos_token_id": None if eos is None else int(eos),
            "priority": int(req.priority),
            "deadline_remaining_s": remaining,
            "tokens": [int(t) for t in req.tokens],
            "admitted": bool(admitted),
            "preemptions": int(req.preemptions),
            "swapped": bool(getattr(req, "swapped", False)),
            "adapter_id": int(getattr(req, "adapter_id", 0)),
            "constraint": None}


# ---------------------------------------------------------------------------
# default engine factory


def tiny_llama_engine(num_layers: int = 2, max_seq_len: int = 64,
                      seed: int = 0, kv_cache_dtype: Optional[str] = None,
                      host_tier: Optional[bool] = None,
                      host_capacity_pages: Optional[int] = None,
                      store=None, **engine_kw):
    """Factory BUILDER for the tiny-llama engine the gates run on:
    returns the zero-arg ``engine_factory`` the supervisor calls at
    construction and after every teardown. Params derive from
    ``jax.random.key(seed)`` alone, so every process in the cluster —
    and the in-process reference cluster in the identity gate —
    materializes bit-identical weights from the spec, no weight
    shipping. ``store`` (a dialed :class:`FabricClient`) routes the
    host tier through the shared fabric."""
    import jax

    from ..inference.predictor import ContinuousBatchingEngine
    from ..models import llama

    cfg = llama.LlamaConfig.tiny(num_layers=num_layers,
                                 max_seq_len=max_seq_len)
    params = llama.init_params(jax.random.key(seed), cfg)
    engine_kw.setdefault("max_batch", 2)
    engine_kw.setdefault("page_size", 8)
    engine_kw.setdefault("max_len", 32)
    engine_kw.setdefault("prefill_chunk", 8)
    tiered = host_tier if host_tier is not None else store is not None
    hkw: Dict = {}
    if host_capacity_pages is not None:
        hkw["host_capacity_pages"] = host_capacity_pages
    if store is not None:
        hkw["store"] = store

    def make():
        return ContinuousBatchingEngine(
            params, cfg, kv_cache_dtype=kv_cache_dtype,
            host_tier=tiered, host_tier_kw=hkw or None, **engine_kw)
    return make


def _resolve_factory(spec: Dict, store):
    """``"module:attr"`` factory-builder resolution; the builder gets
    ``factory_kw`` (plus the fabric ``store`` when the node dialed
    one) and returns the supervisor's zero-arg engine factory."""
    name = spec.get("factory") or \
        "paddle_tpu.serving.node:tiny_llama_engine"
    mod, _, attr = name.partition(":")
    builder = getattr(importlib.import_module(mod), attr)
    kw = dict(spec.get("factory_kw") or {})
    if store is not None:
        kw["store"] = store
    return builder(**kw)


def wait_endpoint(path: str, timeout_s: float = 60.0,
                  process=None) -> Dict:
    """Poll for a worker's ``{"port", "pid"}`` handshake file
    (:func:`~paddle_tpu.serving.fabric.write_endpoint_file`). Raises
    if the deadline lapses or the subprocess exits first."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if process is not None and process.poll() is not None:
            raise RuntimeError(
                f"worker exited rc={process.returncode} before "
                f"publishing its endpoint ({path})")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.02)
    raise TimeoutError(f"no endpoint handshake at {path} within "
                       f"{timeout_s}s")


# ---------------------------------------------------------------------------
# the worker


class ReplicaNode:
    """One replica process: supervisor + scheduler behind RPC.

    Spec keys: ``replica_id``, ``factory`` (``"module:attr"`` builder),
    ``factory_kw``, ``supervisor_kw``, ``wal_dir`` (the durable
    process identity), ``recover`` (rebuild from the WAL dir —
    replacement-after-kill), ``fabric`` (``{"host", "port"}`` of the
    shared KV fabric), ``trace`` (enable the ISSUE 16 tracer and ship
    span batches), ``port_file`` (endpoint handshake path)."""

    def __init__(self, spec: Dict):
        self.spec = dict(spec)
        self.replica_id = int(spec.get("replica_id", 0))
        fab = spec.get("fabric")
        self.fabric: Optional[FabricClient] = None
        if fab:
            page = int((spec.get("factory_kw") or {})
                       .get("page_size", 8))
            self.fabric = FabricClient.dial(
                fab["host"], int(fab["port"]), page_size=page)
        factory = _resolve_factory(spec, self.fabric)
        skw = dict(spec.get("supervisor_kw") or {})
        wal_dir = spec.get("wal_dir")
        recover = bool(spec.get("recover")) and wal_dir \
            and os.path.isdir(wal_dir) and os.listdir(wal_dir)
        if recover:
            self.sup = EngineSupervisor.recover_from_disk(
                factory, wal_dir, **skw)
        else:
            self.sup = EngineSupervisor(factory, wal_dir=wal_dir,
                                        **skw)
        self.sup.replica_id = self.replica_id
        # live handles this node owns; cursors mark the token count /
        # span count the controller has already received
        self._reqs: Dict[int, object] = {}
        self._cursor: Dict[int, int] = {}
        self._spans: Dict[int, int] = {}
        for rid in sorted(getattr(self.sup, "restored", {})):
            self._track(self.sup.restored[rid])
        self.rpc = RpcServer(self, host=spec.get("host", "127.0.0.1"),
                             port=int(spec.get("port", 0)))

    def _track(self, req) -> None:
        self._reqs[req.rid] = req
        self._cursor[req.rid] = len(req.tokens)
        self._spans[req.rid] = 0

    def _untrack(self, rid: int) -> None:
        self._reqs.pop(rid, None)
        self._cursor.pop(rid, None)
        self._spans.pop(rid, None)

    # ---- lifecycle ------------------------------------------------

    @property
    def port(self) -> int:
        return self.rpc.port

    def serve_forever(self) -> None:
        if self.spec.get("port_file"):
            write_endpoint_file(self.spec["port_file"], self.port)
        self.rpc.serve_forever()

    def start(self) -> "ReplicaNode":
        self.rpc.start()
        return self

    def shutdown(self) -> None:
        self.rpc.shutdown()
        if self.fabric is not None:
            self.fabric.close()

    # ---- RPC surface ----------------------------------------------

    def rpc_hello(self, data, blobs):
        """Identity + recovery manifest: the records of every session
        the WAL scan requeued (the controller re-anchors its handles
        to these and lets the deterministic replay re-produce any
        group-commit-lagged tokens)."""
        now = self.sup.clock()
        recovered = [e.as_record(now, None)
                     for e in self.sup.journal.live_entries()] \
            if getattr(self.sup, "restored", None) else []
        return {"replica_id": self.replica_id, "pid": os.getpid(),
                "page_size": int(self.sup.engine.cache.page_size),
                "health": self.sup.health,
                "recovered": recovered}

    def rpc_submit_request(self, data, blobs):
        """Journaled intake of a request record — fresh dispatch and
        failover rehome alike (``admitted`` in the record selects the
        preempted-resume rebuild, exactly as recovery does)."""
        rec = data["record"]
        req = _session_from_record(self.sup, rec, None)
        if data.get("trace") is not None:
            _obs.serving_trace_submit(req, replica=self.replica_id)
        self.sup.submit_request(req)
        if not req.done:
            self._track(req)
        return {"done": bool(req.done),
                "finish_reason": req.finish_reason}

    def rpc_step(self, data, blobs):
        """One supervised scheduler step; the reply carries per-request
        token APPEND deltas past each controller cursor, final
        done/finish states, and — with tracing on — the span dicts
        recorded since the last ship (the cross-process stitch)."""
        has_work = self.sup.step()
        updates: List[Dict] = []
        spans: List[Dict] = []
        finished: List[int] = []
        for rid, req in self._reqs.items():
            cur = self._cursor[rid]
            if len(req.tokens) < cur:
                # a recovery rewound committed-but-unsynced tokens;
                # resync the controller with a full replacement
                updates.append({"rid": rid, "reset": True,
                                "tokens": [int(t) for t in req.tokens],
                                "done": bool(req.done),
                                "finish_reason": req.finish_reason})
                self._cursor[rid] = len(req.tokens)
            elif len(req.tokens) > cur or req.done:
                updates.append(
                    {"rid": rid,
                     "tokens": [int(t) for t in req.tokens[cur:]],
                     "done": bool(req.done),
                     "finish_reason": req.finish_reason})
                self._cursor[rid] = len(req.tokens)
            tr = getattr(req, "trace", None)
            if tr is not None:
                all_spans = list(tr.spans)
                seen = self._spans.get(rid, 0)
                if len(all_spans) < seen:        # ring wrapped
                    seen = 0
                for s in all_spans[seen:]:
                    d = s.to_dict()
                    d["rid"] = rid
                    spans.append(d)
                self._spans[rid] = len(all_spans)
            if req.done:
                finished.append(rid)
        for rid in finished:
            self._untrack(rid)
        return {"has_work": bool(has_work), "health": self.sup.health,
                "updates": updates, "spans": spans}

    def rpc_load_stats(self, data, blobs):
        return self.sup.load_stats()

    def rpc_handoff_ready(self, data, blobs):
        """Rids whose prefill completed and whose slot is not
        mid-chunk — the prefill side of the harvest scan."""
        eng = self.sup.engine
        rids = [int(r.rid) for r in eng.running_requests()
                if not r.done and r.tokens
                and r.slot not in eng._pending
                and r.rid in self._reqs]
        return {"rids": rids}

    def rpc_export_prefilled(self, data, blobs):
        """Pure-read export of a running slot's live pages; the KV
        entry rides as blobs. The reply also carries the node's
        CURRENT token list — the adopt record must be built from the
        exporter's exact state, not the controller's possibly-older
        view."""
        req = self._reqs[int(data["rid"])]
        payload = self.sup.engine.export_prefilled(req, with_kv=True)
        out, oblobs = {}, None
        out["slot"] = int(payload["slot"])
        out["length"] = int(payload["length"])
        out["last"] = int(payload["last"])
        out["tokens"] = [int(t) for t in req.tokens]
        kv_data, oblobs = entry_to_wire(payload["kv"])
        out["kv"] = kv_data
        return out, oblobs

    def rpc_adopt_prefilled(self, data, blobs):
        """Decode-side import + journal adoption in ONE exchange:
        rebuild a clean handle from the record, install the shipped
        pages (CRC-verified before any scatter — a corrupt payload
        raises ``CorruptionDetected`` as a typed envelope and commits
        nothing), then ``adopt_running``. ``ok=False`` means no free
        slot — the controller offers the payload elsewhere."""
        rec = dict(data["record"])
        rec["admitted"] = False     # adopt_running journals admission
        req = _session_from_record(self.sup, rec, None)
        # node-local trace so decode-side spans record here and ship
        # to the controller's stitched trace
        _obs.serving_trace_submit(req, replica=self.replica_id)
        payload = {"rid": int(rec["rid"]), "slot": int(data["slot"]),
                   "length": int(data["length"]),
                   "last": int(data["last"]),
                   "kv": entry_from_wire(data["kv"], blobs)}
        if not self.sup.engine.import_prefilled(req, payload):
            return {"ok": False}
        self.sup.adopt_running(req)
        self._track(req)
        return {"ok": True, "slot": int(req.slot)}

    def rpc_finish_handoff(self, data, blobs):
        """Prefill-side detach after a successful adopt elsewhere:
        durable journal tombstone first, then slot-clear +
        page-release (the same clear-before-release ordering the
        in-process handoff relies on)."""
        rid = int(data["rid"])
        req = self._reqs.get(rid)
        if req is None:
            return {"ok": False}
        self.sup.journal.forget(rid)
        self.sup.engine.finish_handoff(req, int(data["slot"]))
        self._untrack(rid)
        return {"ok": True}

    def rpc_forget(self, data, blobs):
        """Durably drop a session this node must NOT serve (the
        controller's post-recovery dedupe: the handle already finished
        elsewhere, or a rehomed copy supersedes this one)."""
        rid = int(data["rid"])
        req = self._reqs.get(rid)
        self.sup.journal.forget(rid)
        if req is not None:
            try:
                self.sup.engine.cancel_request(req, "superseded")
            except Exception:  # noqa: BLE001 - already gone is fine
                pass
            self._untrack(rid)
        return {"ok": True}

    def rpc_drain(self, data, blobs):
        """Retirement: checkpoint to ``path`` and hand back the live
        session records for the controller to rehome. Drain FIRST —
        it commits any in-flight overlapped step and syncs the
        journal, so the records carry every token the device already
        produced."""
        summary = self.sup.drain(data["path"])
        now = self.sup.clock()
        summary["records"] = [e.as_record(now, None)
                              for e in self.sup.journal.live_entries()]
        return summary

    def rpc_tier_stats(self, data, blobs):
        cache = self.sup.engine.cache
        out = {"tier": cache.tier_stats()
               if hasattr(cache, "tier_stats") else {}}
        alloc = cache.allocator
        if data.get("drop_prefix") and cache.prefix is not None:
            # the balanced-allocator gate (chaos soak): standing
            # prefix-trie pages are intentionally resident — release
            # them so num_used == 0 is assertable after a drain
            cache.prefix.drop_all(alloc)
        out["allocator"] = alloc.stats()
        if self.fabric is not None:
            out["fabric_client"] = {
                "puts_total": self.fabric.puts_total,
                "hits_total": self.fabric.hits_total,
                "misses_total": self.fabric.misses_total,
                "quarantined_total": self.fabric.quarantined_total,
                "unreachable_total": self.fabric.unreachable_total}
        return out

    def rpc_ping(self, data, blobs):
        return {"ok": True, "pid": os.getpid(),
                "health": self.sup.health}

    def rpc_shutdown(self, data, blobs):
        import threading
        threading.Timer(0.05, self.shutdown).start()
        return {"ok": True}


# ---------------------------------------------------------------------------
# worker-process entry


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="paddle_tpu serving replica worker")
    p.add_argument("--spec", required=True,
                   help="path to the JSON ReplicaNode spec")
    args = p.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    cache_dir = spec.get("xla_cache_dir")
    if cache_dir:
        # the tier-1 harness's persistent compilation cache
        # (tests/conftest.py): worker processes compile the same tiny
        # programs the parent already did — dedupe them
        try:
            import jax
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass
    if spec.get("trace"):
        from ..observability import tracing
        tracing.enable()
    if spec.get("metrics"):
        from .. import observability as obs
        obs.enable()
    node = ReplicaNode(spec)
    node.serve_forever()
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(main())
