"""Disaggregated serving cluster: engine replicas behind a
prefix-affinity router, with prefill→decode KV handoff (ISSUE 9).

The PR 2–8 stack tops out at ONE engine — one pool of HBM, one blast
radius, no way to upgrade without dropping sessions.
:class:`ServingCluster` is the horizontal layer above it: N
:class:`~paddle_tpu.serving.EngineSupervisor`-wrapped replicas (each
optionally tp-sharded) behind a
:class:`~paddle_tpu.serving.router.ClusterRouter`.

- **Routing** — submissions queue at the cluster and dispatch in
  per-tenant fair-share order (ascending token account); placement is
  prefix-affinity first (the prompt's leading full pages hash to the
  replica whose :class:`~paddle_tpu.serving.PrefixCache` trie already
  holds the tenant's system prompt), least-loaded/healthiest otherwise,
  read from the PUBLIC
  :meth:`~paddle_tpu.serving.ServingScheduler.load_stats` snapshot
  (and mirrored to the metrics registry as the ``serving_replica_*``
  gauges) — the router never reaches into engine internals. Per-tenant
  :class:`~paddle_tpu.serving.router.TenantQuota` rate limits reject
  over-quota submissions with the structured ``rejected_ratelimit``
  finish reason before any replica sees them; a request a degraded
  replica sheds (``rejected_overload``) re-dispatches to untried
  replicas under a per-request retry budget and per-tenant retry-rate
  cap (ISSUE 13) before the rejection surfaces
  (``serving_router_retries_total`` /
  ``serving_router_retry_exhausted_total``).

- **Prefill/decode disaggregation** (``prefill_replicas > 0``) —
  dedicated prefill replicas run chunked prefill to completion, then
  hand the finished pages to a decode replica:
  :meth:`~paddle_tpu.serving.PagedKVCache.export_request` (raw page
  bytes of the request's ARBITRARY block table — the PR 8
  ``checkpoint_prefix`` machinery generalized past trie chains) →
  :meth:`~paddle_tpu.serving.PagedKVCache.import_request` (one jitted
  donated scatter into the decode pool). The handoff is BIT-identical
  to prefilling in place at fp and int8-KV, including tp-sharded
  replicas (tests/test_cluster.py); when no decode slot is free the
  prefill replica simply keeps serving the request — disaggregation is
  an optimization, never a stall.

- **Failover & rolling upgrade** — a replica whose circuit opens
  (:class:`~paddle_tpu.serving.EngineDead`) is rebuilt in place and its
  journaled sessions re-dispatch onto survivors (resume semantics:
  token-identical replay, zero lost requests —
  tools/chaos_soak.py --cluster); :meth:`retire_replica` drains one
  replica through the PR 8 drain path, requeues its sessions elsewhere
  MID-DECODE, and restores the drained prefix trie into the
  replacement so the tenant's next prompt still prefix-HITs.

- **Overload hardening (ISSUE 13)** — an optional
  :class:`~paddle_tpu.serving.router.AdmissionController` sheds
  deadline-infeasible submissions at the door
  (``rejected_infeasible``), a :class:`ClusterAutoscaler` breathes the
  decode-replica count with backlog + degraded rungs (hysteresis +
  cooldown; scale-down drains through :meth:`retire_replica`, so
  sessions rehome with zero loss), and the handoff verifies payload
  CRCs before install (a corrupt payload is detected, counted, and
  the request keeps decoding on its prefill replica) with bounded
  idempotent retries on transient import faults.

Token identity holds by construction: per-request greedy decode is
independent of batch composition (the PR 2–7 parity gates), so routed
output matches a single engine serving the same request set
bit-for-bit — gated in tests/test_cluster.py.
"""
from __future__ import annotations

import os
import tempfile
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from ..observability import hooks as _obs
from .host_tier import _tampered_entry
from .paged_cache import PoolExhausted
from .policy import FinishReason, Priority
from .resilience import (CorruptionDetected, EngineDead,
                         EngineSupervisor, StepStalled, fault_point,
                         load_drain_checkpoint, run_with_deadline,
                         tamper_point)
from .router import AdmissionController, ClusterRouter, TenantQuota


class ClusterAutoscaler:
    """Hysteresis policy + state for the cluster's closed scaling loop
    (ISSUE 13): each :meth:`ServingCluster.step` feeds it the decode
    tier's backlog-per-serviceable-replica and worst degraded rung, and
    it answers ``"up"`` / ``"down"`` / ``None``.

    Flap-proofing is structural: scale-up needs ``up_after``
    CONSECUTIVE over-threshold ticks (backlog at or above
    ``up_backlog_per_replica``, or any replica at or past
    ``degraded_rung_trigger`` — a rung that deep means the PR 8 ladder
    is already shedding, so more silicon beats more shedding), scale-
    down needs ``down_after`` consecutive under-threshold ticks with
    every replica healthy, the two thresholds leave a dead band
    between them, and ANY action starts a ``cooldown_ticks`` refractory
    window. ``min_replicas``/``max_replicas`` bound the serviceable
    decode-replica count."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4, *,
                 up_backlog_per_replica: float = 4.0,
                 down_backlog_per_replica: float = 0.5,
                 up_after: int = 2, down_after: int = 4,
                 cooldown_ticks: int = 8,
                 degraded_rung_trigger: int = 2):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"ClusterAutoscaler: need 1 <= min_replicas="
                f"{min_replicas} <= max_replicas={max_replicas}")
        if down_backlog_per_replica >= up_backlog_per_replica:
            raise ValueError(
                f"ClusterAutoscaler: down threshold "
                f"{down_backlog_per_replica} must sit strictly below "
                f"the up threshold {up_backlog_per_replica} — the dead "
                f"band between them is the anti-flap margin")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_backlog = float(up_backlog_per_replica)
        self.down_backlog = float(down_backlog_per_replica)
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.degraded_rung_trigger = int(degraded_rung_trigger)
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self.up_events = 0
        self.down_events = 0

    def decide(self, backlog_per_replica: float, serviceable: int,
               max_rung: int) -> Optional[str]:
        """One tick's decision; mutates the hysteresis state."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        pressure = (backlog_per_replica >= self.up_backlog
                    or max_rung >= self.degraded_rung_trigger)
        calm = (backlog_per_replica <= self.down_backlog
                and max_rung == 0)
        if pressure:
            self._up_streak += 1
            self._down_streak = 0
        elif calm:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # the dead band: neither streak advances, neither resets
            # the other's progress to zero-and-back flapping
            self._up_streak = 0
            self._down_streak = 0
        if (pressure and self._up_streak >= self.up_after
                and serviceable < self.max_replicas):
            self._up_streak = 0
            self._cooldown = self.cooldown_ticks
            self.up_events += 1
            return "up"
        if (self._down_streak >= self.down_after
                and serviceable > self.min_replicas):
            self._down_streak = 0
            self._cooldown = self.cooldown_ticks
            self.down_events += 1
            return "down"
        return None

    def stats(self) -> Dict:
        return {"up_events": self.up_events,
                "down_events": self.down_events,
                "cooldown_remaining": self._cooldown,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas}


class ServingCluster:
    """N supervised engine replicas behind a cluster router.

    ``engine_factory() -> ContinuousBatchingEngine`` builds one FRESH
    replica engine (identical config each call — the same contract
    :class:`~paddle_tpu.serving.EngineSupervisor` already imposes;
    replicas share the params tree read-only). ``prefill_replicas``
    carves the first K replicas out as dedicated prefill engines
    (0 = every replica serves end-to-end). ``quotas`` maps tenant ->
    :class:`~paddle_tpu.serving.router.TenantQuota`. ``supervisor_kw``
    passes through to every replica's supervisor (watchdog, backoff,
    circuit threshold). ``clock`` is shared by the router, every
    scheduler and every supervisor so deadlines mean one thing
    cluster-wide.
    """

    def __init__(self, engine_factory: Callable, replicas: int = 2, *,
                 prefill_replicas: int = 0,
                 token_budget: Optional[int] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 router: Optional[ClusterRouter] = None,
                 clock: Callable[[], float] = time.monotonic,
                 supervisor_kw: Optional[Dict] = None,
                 share_host_tier: bool = True,
                 direct_handoff: bool = False,
                 overlap: Optional[bool] = None,
                 admission: Optional[AdmissionController] = None,
                 autoscaler: Optional[ClusterAutoscaler] = None,
                 handoff_retries: int = 2,
                 handoff_timeout_s: Optional[float] = None,
                 retry_sleep: Callable[[float], None] = time.sleep,
                 wal_dir: Optional[str] = None,
                 _recover: bool = False):
        if replicas < 1:
            raise ValueError(f"replicas={replicas} must be >= 1")
        if not 0 <= prefill_replicas < replicas:
            raise ValueError(
                f"prefill_replicas={prefill_replicas} must leave at "
                f"least one decode replica (replicas={replicas})")
        self._factory = engine_factory
        self.token_budget = token_budget
        self.clock = clock
        self._sup_kw = dict(supervisor_kw or {})
        # crash-durable cluster (ISSUE 15): wal_dir gives EVERY replica
        # its own journal directory (replica<i>/) — failover
        # replacements adopt the dead replica's directory (journal
        # continuity), and recover_from_disk() rebuilds the whole
        # cluster after whole-process death, replica by replica
        self.wal_dir = wal_dir
        self._recovering = bool(_recover)
        if overlap is not None:
            # async overlapped runtime (ISSUE 12): every supervised
            # replica's scheduler runs the double-buffered pipeline —
            # threaded through scheduler_kw so supervisor rebuilds
            # (failover, retirement replacements) keep the mode. None
            # defers to the factory's engines (their overlap knob).
            kw = dict(self._sup_kw.get("scheduler_kw") or {})
            kw["overlap"] = bool(overlap)
            self._sup_kw["scheduler_kw"] = kw
        self.overlap = overlap
        self._next_rid = 0
        self._host_store = None
        self.replicas: List[EngineSupervisor] = [
            self._new_supervisor(i) for i in range(replicas)]
        self._recovering = False
        if share_host_tier:
            # hierarchical KV (ISSUE 10): when the factory builds
            # host-tiered engines, every replica shares ONE
            # HostPageStore — rids are cluster-unique, and page bytes
            # are position-addressed, so a session swapped out on a
            # dying replica SWAPS IN on whichever replica it rehomes
            # to (no replay), and a failover/retirement replacement
            # starts warm from the standing prefix tier
            store = getattr(self.replicas[0].engine.cache, "host", None)
            if store is not None:
                self._host_store = store
                for sup in self.replicas[1:]:
                    self._attach_host_store(sup)
        self.prefill_replicas = prefill_replicas
        page = self.replicas[0].engine.cache.page_size
        for sup in self.replicas[1:]:
            if sup.engine.cache.page_size != page:
                raise ValueError(
                    "engine_factory returned replicas with different "
                    "page sizes — handoff and affinity need one "
                    "geometry")
        self.router = router if router is not None else ClusterRouter(
            page, quotas=quotas, clock=clock)
        self._rq: List[Dict] = []       # undispatched submissions
        self._live: Dict[int, object] = {}  # rid -> live request handle
        self._meta: Dict[int, Dict] = {}  # rid -> {tenant, cost}
        self._owner: Dict[int, int] = {}  # rid -> replica idx
        # fused prefill→decode handoff (ISSUE 11): replicas sharing this
        # process copy pages device-to-device through the donated
        # serving.paged_cache._pool_move program instead of staging raw
        # bytes through host numpy — byte-identical, gated in
        # tests/test_lowbit_decode.py. Opt-in: cross-host clusters (and
        # the PR 9 byte-payload gates) keep the host-staged path.
        self.direct_handoff = bool(direct_handoff)
        self._seq = 0
        self._steps = 0
        # SLO-guarded admission + autoscaling (ISSUE 13): the
        # controller sheds deadline-infeasible submissions at the door
        # (rejected_infeasible — BEFORE the PR 8 degraded ladder pays
        # for them), the autoscaler breathes the decode-replica count
        # with load through the existing retire_replica drain path
        self.admission = admission
        self.autoscaler = autoscaler
        # bounded idempotent handoff retry (+ optional per-import
        # deadline): a transient decode-side import fault retries with
        # backoff before it costs that replica a recovery
        self.handoff_retries = int(handoff_retries)
        self.handoff_timeout_s = handoff_timeout_s
        self._retry_sleep = retry_sleep
        self.handoffs_total = 0
        self.handoff_retries_total = 0
        self.handoff_corruptions_total = 0
        self.autoscale_faults_total = 0
        self.failovers_total = 0
        self.retirements_total = 0
        self.deadline_cancels_total = 0

    def _replica_wal_dir(self, idx: int) -> Optional[str]:
        if self.wal_dir is None:
            return None
        return os.path.join(self.wal_dir, f"replica{idx:03d}")

    def _new_supervisor(self, idx: int) -> EngineSupervisor:
        kw = dict(self._sup_kw)
        wdir = self._replica_wal_dir(idx)
        if wdir is not None:
            kw.setdefault("wal_dir", wdir)
        if wdir is not None and self._recovering \
                and os.path.isdir(wdir) and os.listdir(wdir):
            # cold cluster recovery: the replica adopts its (or its
            # dead predecessor's) journal directory wholesale — torn
            # tail repaired, checkpoint + suffix replayed, sessions
            # requeued through the resume path
            sup = EngineSupervisor.recover_from_disk(
                self._factory, wdir,
                token_budget=self.token_budget, clock=self.clock,
                **{k: v for k, v in kw.items() if k != "wal_dir"})
        else:
            sup = EngineSupervisor(self._factory,
                                   token_budget=self.token_budget,
                                   clock=self.clock, **kw)
        sup.engine._next_rid = max(sup.engine._next_rid, self._next_rid)
        self._next_rid = max(self._next_rid, sup.engine._next_rid)
        # replica identity for trace spans + flight dumps (ISSUE 16):
        # the supervisor propagates it into scheduler/engine and
        # re-stamps across its own rebuilds
        sup.replica_id = idx
        self._attach_host_store(sup)
        return sup

    def _attach_host_store(self, sup: EngineSupervisor) -> None:
        """Point a (tiered) replica's cache at the cluster-shared
        :class:`~paddle_tpu.serving.host_tier.HostPageStore`; the
        supervisor's own rebuilds then carry it forward
        (``adopt_host_tier``), so the share survives recoveries."""
        store = getattr(self, "_host_store", None)
        if store is not None and hasattr(sup.engine.cache, "host"):
            sup.engine.cache.host = store

    # ---- roles ----
    def _prefill_idxs(self) -> List[int]:
        return list(range(self.prefill_replicas))

    def _decode_idxs(self) -> List[int]:
        return list(range(self.prefill_replicas, len(self.replicas)))

    def _alive(self, idxs) -> Dict[int, Dict]:
        """load_stats snapshots of the serviceable replicas among
        ``idxs`` — the router's whole worldview."""
        out = {}
        for i in idxs:
            sup = self.replicas[i]
            if sup.health == "dead" or sup._draining:
                continue
            out[i] = sup.load_stats()
        return out

    # ---- intake ----
    def submit(self, prompt, max_new_tokens: int = 16, *,
               tenant: str = "default", priority=Priority.NORMAL,
               deadline_s: Optional[float] = None, eos_token_id=None,
               adapter_id: int = 0, constraint=None):
        """Queue a prompt for routed dispatch. The handle fills in as
        cluster steps run, exactly like a single engine's. Over-quota
        tenants get an immediate ``rejected_ratelimit``; everything
        else dispatches on the next :meth:`step` in fair-share order.

        ``adapter_id`` (ISSUE 14): the request's LoRA variant — every
        replica must have been built with an adapter pool over a
        SHARED registry (the factory closes over one
        :class:`~paddle_tpu.serving.adapters.AdapterRegistry`), so any
        replica can load the adapter and the router is free to place
        by affinity. ``constraint``: a per-request grammar
        (``constraints=True`` engines)."""
        eng = self.replicas[self._first_alive()].engine
        eng._next_rid = max(eng._next_rid, self._next_rid)
        req = eng.create_request(prompt, max_new_tokens=max_new_tokens,
                                 eos_token_id=eos_token_id,
                                 adapter_id=adapter_id,
                                 constraint=constraint)
        self._next_rid = eng._next_rid
        req.priority = int(priority)
        cost = req.prompt.shape[1] + req.max_new_tokens
        self._live[req.rid] = req
        self._meta[req.rid] = {"tenant": tenant, "cost": cost}
        # trace minted at CLUSTER intake (ISSUE 16) — replica -1 is the
        # router lane; the handle carries the trace through dispatch,
        # handoff and failover rehomes, stitching them into one trace
        _obs.serving_trace_submit(req)
        if not self.router.admit_rate_limit(tenant, cost):
            req.done = True
            req.finish_reason = FinishReason.REJECTED_RATELIMIT.value
            self.router.note_ratelimited(tenant)
            _obs.serving_cancelled(1, req.finish_reason)
            _obs.serving_trace_finish(req, req.finish_reason)
            return req
        if deadline_s is not None and self.admission is not None:
            # SLO-guarded admission (ISSUE 13): feasibility is judged
            # against the tier that will produce this request's FIRST
            # token — fresh submissions dispatch to the prefill tier
            # when one exists (_dispatch_one's role rule), so an idle
            # decode replica must not mask a buried prefill queue.
            # The load_stats walk (O(queued requests) per replica)
            # only runs when the service-rate model is on; without
            # tokens_per_s feasible() never reads the loads.
            if self.admission.tokens_per_s is not None:
                role = (self._prefill_idxs() if self.prefill_replicas
                        else self._decode_idxs())
                loads = (self._alive(role) or self._alive(
                    range(len(self.replicas)))).values()
            else:
                loads = ()
            if not self.admission.feasible(
                    float(deadline_s), req.prompt.shape[1], loads):
                # the deadline cannot be met against current backlog —
                # reject at the door instead of queueing work that will
                # expire (or push replicas onto the degraded ladder)
                # without ever producing goodput
                req.done = True
                req.finish_reason = FinishReason.REJECTED_INFEASIBLE.value
                self.router.note_slo_rejected(tenant)
                _obs.serving_cancelled(1, req.finish_reason)
                _obs.serving_trace_finish(req, req.finish_reason)
                return req
        if deadline_s is not None:
            req.deadline_at = self.clock() + float(deadline_s)
        _obs.serving_trace_enqueued(req)
        self._rq.append({"req": req, "tenant": tenant, "cost": cost,
                         "seq": self._seq})
        self._seq += 1
        return req

    def _first_alive(self) -> int:
        for i, sup in enumerate(self.replicas):
            if sup.health != "dead" and not sup._draining:
                return i
        raise EngineDead("every replica in the cluster is dead")

    # ---- dispatch ----
    def _dispatch(self):
        """Drain the router queue in fair-share order: per-tenant FIFO
        deques, always serving the tenant with the smallest token
        account next (ties break on submission order) — O(n log n)
        over the whole queue, and the ordering bound the fairness
        guarantee rests on: a light tenant's request outranks every
        request of any tenant that already consumed more. Dispatch =
        journaled intake on the chosen replica
        (:meth:`~paddle_tpu.serving.EngineSupervisor.submit_request`);
        a shed (``rejected_overload``) dispatch retries on untried
        replicas up to the router's per-request retry budget, bounded
        by the tenant's retry-rate cap. Queued requests whose deadline
        lapsed
        at the router cancel here — the same admission SLO the replica
        schedulers enforce."""
        if not self._rq:
            return
        now = self.clock()
        by_tenant: Dict[str, Deque] = {}
        for e in self._rq:              # already in ascending seq order
            by_tenant.setdefault(e["tenant"], deque()).append(e)
        self._rq = []
        accounts = self.router.accounts
        while by_tenant:
            tenant = min(by_tenant,
                         key=lambda t: (accounts.get(t, 0),
                                        by_tenant[t][0]["seq"]))
            q = by_tenant[tenant]
            e = q.popleft()
            if not q:
                del by_tenant[tenant]
            req = e["req"]
            if req.done:
                continue
            if req.deadline_at is not None and now >= req.deadline_at:
                req.done = True
                req.finish_reason = FinishReason.DEADLINE_EXCEEDED.value
                self.deadline_cancels_total += 1
                _obs.serving_cancelled(1, req.finish_reason)
                _obs.serving_trace_finish(req, req.finish_reason)
                continue
            self._dispatch_one(e)

    def _dispatch_one(self, entry: Dict):
        req = entry["req"]
        tenant = entry["tenant"]
        fresh = not req.tokens and req.preemptions == 0
        role = (self._prefill_idxs()
                if self.prefill_replicas and fresh
                else self._decode_idxs())
        loads = self._alive(role) or self._alive(
            range(len(self.replicas)))
        key = self.router.affinity_key(req.prompt[0])
        akey = self.router.adapter_key(getattr(req, "adapter_id", 0))
        idx, hit = self.router.pick_replica(key, loads,
                                            adapter_key=akey)
        _obs.serving_trace_mark(req, "dispatch", replica=idx,
                                meta={"affinity_hit": bool(hit),
                                      "tenant": tenant})
        self.replicas[idx].submit_request(req)
        self.router.note_dispatch(idx, hit, tenant)
        self._owner[req.rid] = idx

        def shed():
            return (req.done and req.finish_reason
                    == FinishReason.REJECTED_OVERLOAD.value)
        # router-level retry of shed work (ISSUE 13 satellite): a
        # per-request budget of re-dispatches to untried replicas
        # (ignore affinity — the bound replica just proved it cannot
        # take new work), bounded by the tenant's retry-rate cap so a
        # degraded replica cannot amplify one tenant's burst into a
        # cluster-wide retry storm. Exhaustion (budget/cap ran out, or
        # every replica tried) counts separately from a first-try
        # rejection with nowhere else to go.
        tried = {idx}
        attempts = 0
        while (shed() and len(loads) > len(tried)
               and self.router.may_retry(tenant, attempts)):
            self.router.note_retry(tenant)
            attempts += 1
            req.done = False
            req.finish_reason = None
            idx2, _ = self.router.pick_replica(None, loads,
                                               exclude=tried)
            _obs.serving_trace_mark(req, "dispatch_retry", replica=idx2)
            self.replicas[idx2].submit_request(req)
            self.router.note_dispatch(idx2, False, tenant)
            tried.add(idx2)
            self._owner[req.rid] = idx2
        if shed():
            req.finish_reason = FinishReason.REJECTED_OVERLOAD.value
            if attempts > 0 or (len(loads) > len(tried)
                                and not self.router.may_retry(
                                    tenant, attempts)):
                self.router.note_retry_exhausted()
        else:
            # the fair-share account charges only work a replica
            # actually accepted — a tenant whose requests are shed
            # during a degraded blip must not also sink in the
            # dispatch order for service it never received
            self.router.charge(tenant, entry["cost"])

    # ---- stepping ----
    def step(self) -> bool:
        """One cluster step: dispatch the router queue, step every
        serviceable replica (a replica whose circuit opens fails over
        in place), harvest completed prefills into decode replicas,
        publish replica load gauges. Returns False when no work remains
        anywhere."""
        self._dispatch()
        for i in range(len(self.replicas)):
            sup = self.replicas[i]
            if sup.health == "dead" or sup._draining:
                continue
            try:
                sup.step()
            except EngineDead:
                self._failover(i)
        if self.prefill_replicas:
            self._harvest_handoffs()
        self._autoscale_tick()
        self._publish()
        self._prune_finished()
        self._steps += 1
        return self._has_work()

    def run(self) -> None:
        """Drive steps until every submitted request finished."""
        while self.step():
            pass

    def _prune_finished(self) -> None:
        """Drop router bookkeeping for finished requests (the results
        live on the callers' handles) — without this, _live/_meta/
        _owner would grow with every request ever served, the same
        leak the RequestJournal's sync() avoids."""
        for rid in [r for r, req in self._live.items() if req.done]:
            del self._live[rid]
            self._meta.pop(rid, None)
            self._owner.pop(rid, None)

    def _has_work(self) -> bool:
        if any(not e["req"].done for e in self._rq):
            return True
        for sup in self.replicas:
            if sup.health == "dead" or sup._draining:
                continue
            if (any(sup.scheduler._queues.values())
                    or not sup.engine.idle):
                return True
        return False

    def _publish(self):
        """Refresh the ``serving_replica_*`` gauges — the metrics
        registry is the cluster's signal bus (PR 1): replicas publish,
        dashboards (and any external balancer) read."""
        if not _obs.enabled:
            return
        for i, sup in enumerate(self.replicas):
            s = sup.load_stats()
            _obs.serving_router_replica(
                i, s["queued_total"], s["pool_occupancy"],
                s["degraded_level"])

    # ---- autoscaling (ISSUE 13) ----
    def _spawn_replica(self) -> int:
        """Install one fresh decode replica: reuse a drained/dead husk
        slot first (replica INDICES are identity — the owner map and
        affinity bindings key on them, so the list must not shift),
        else append. The fresh supervisor shares the cluster host
        tier/clock like any construction-time replica."""
        for i in self._decode_idxs():
            sup = self.replicas[i]
            if sup.health == "dead" or sup._draining:
                self.replicas[i] = self._new_supervisor(i)
                self.router.drop_replica(i)
                return i
        self.replicas.append(self._new_supervisor(len(self.replicas)))
        return len(self.replicas) - 1

    def _autoscale_tick(self):
        """One closed-loop scaling decision (no-op without an
        :class:`ClusterAutoscaler`): feed the decode tier's backlog
        per serviceable replica + worst degraded rung through the
        hysteresis policy; ``up`` installs a fresh replica, ``down``
        retires the least-loaded one through the PR 9
        :meth:`retire_replica` drain path — its sessions rehome
        MID-DECODE with resume semantics, so scale-down loses and
        duplicates nothing (the soak gate). The tick itself is a
        best-effort control plane: a fault here (the
        ``autoscale_tick`` site) skips ONE decision and the next step
        re-evaluates from fresh signals — it must never take serving
        down with it."""
        if self.autoscaler is None:
            return
        try:
            fault_point("autoscale_tick")
        except Exception:
            self.autoscale_faults_total += 1
            return
        # one load_stats pass over the whole fleet (load_stats walks
        # every queued request since queued_tokens landed — the decode
        # subset is derived, not re-computed)
        every = self._alive(range(len(self.replicas)))
        alive = {i: s for i, s in every.items()
                 if i >= self.prefill_replicas}
        if not alive:
            return
        # pressure signal: the WHOLE cluster's undone work (router
        # queue + every serviceable replica's queues — a disaggregated
        # prefill replica's backlog is future decode work in disguise)
        # over the decode capacity the autoscaler actually controls
        backlog = (
            sum(1 for e in self._rq if not e["req"].done)
            + sum(s["queued_total"] + s["pending_prefills"]
                  for s in every.values()))
        per = backlog / len(alive)
        max_rung = max(s["degraded_level"] for s in every.values())
        action = self.autoscaler.decide(per, len(alive), max_rung)
        if action == "up":
            self._spawn_replica()
            _obs.serving_autoscale("up", len(alive) + 1, per)
        elif action == "down":
            # retire the healthiest/least-loaded replica: fewest live
            # sessions to rehome, and the survivors keep the hot tries
            victim = min(alive,
                         key=lambda i: self.router._score(alive[i])
                         + (i,))
            self.retire_replica(victim, replace=False)
            _obs.serving_autoscale("down", len(alive) - 1, per)

    # ---- prefill→decode handoff ----
    def _harvest_handoffs(self):
        """Move every decode-ready request off the prefill replicas:
        export the slot's live pages (pure read), import + journal them
        on a decode replica, then detach from the prefill side
        (slot-clear before page-release, so no fault can leave two
        engines decoding one request). A request that cannot place (no
        free decode slot / pool full) stays on its prefill replica and
        keeps decoding there — the handoff is opportunistic."""
        decode = self._alive(self._decode_idxs())
        if not decode:
            return
        for i in self._prefill_idxs():
            sup = self.replicas[i]
            if sup.health == "dead" or sup._draining:
                continue
            eng = sup.engine
            for req in list(eng.running_requests()):
                if (req.done or not req.tokens
                        or req.slot in eng._pending):
                    continue
                try:
                    self._handoff_one(sup, req, decode)
                except EngineDead:
                    self._failover(i)
                    break
                except Exception as exc:  # noqa: BLE001 — injected or
                    # real fault on the PREFILL side of the handoff
                    # (page release inside finish_handoff; decode-side
                    # faults are attributed inside _handoff_one): route
                    # it through the prefill supervisor's
                    # classify+recover machinery, same as a step fault.
                    # The request is safe: finish_handoff clears the
                    # slot before anything fallible, and the journal
                    # already moved to the decode side.
                    try:
                        sup._on_failure(exc)
                    except EngineDead:
                        self._failover(i)
                    # recovery REBUILT the engine: the remaining
                    # snapshot entries are no longer running there
                    # (they were requeued), so exporting them now
                    # would raise and masquerade as fresh failures —
                    # stop and let the next step re-harvest
                    break

    def _handoff_one(self, sup, req, decode_loads: Dict[int, Dict]):
        eng = sup.engine
        direct = self.direct_handoff
        t0 = _obs.generate_begin()
        # export-side fault site (ISSUE 13): fires before the pure
        # read — a fault here commits nothing and routes through the
        # PREFILL supervisor's recovery (the _harvest_handoffs catch)
        fault_point("handoff_export")
        src = getattr(sup, "replica_id", -1)
        tx = _obs.serving_trace_now()
        # pure host-side read; the direct path exports metadata only —
        # the page bytes move device-to-device inside the import
        payload = eng.export_prefilled(req, with_kv=not direct)
        if not direct and tamper_point("handoff_export"):
            # injected payload corruption: real bytes flip here, the
            # import-side CRC verifier must catch them before install
            payload["kv"] = _tampered_entry(payload["kv"])
        pages = eng.cache.pages_for(payload["length"])
        nbytes = (eng.cache.page_payload_bytes(pages) if direct else
                  sum(a.nbytes for a in payload["kv"]["arrays"].values()))
        _obs.serving_handoff_export(t0, nbytes, pages)
        _obs.serving_trace_span(req, "handoff_export", tx, replica=src,
                                slot=payload["slot"],
                                seq=len(req.tokens),
                                meta={"bytes": int(nbytes),
                                      "pages": int(pages)})
        placed = None
        for didx in sorted(decode_loads,
                           key=lambda d: self.router._score(
                               decode_loads[d]) + (d,)):
            dsup = self.replicas[didx]
            t1 = _obs.generate_begin()
            t1t = _obs.serving_trace_now()
            attempts = 0
            while True:
                try:
                    fault_point("handoff_import")
                    if run_with_deadline(
                            lambda: dsup.engine.import_prefilled(
                                req, payload,
                                src_engine=eng if direct else None),
                            self.handoff_timeout_s):
                        placed = didx
                        _obs.serving_handoff_import(t1)
                        _obs.serving_trace_span(
                            req, "handoff_import", t1t, replica=didx,
                            slot=(req.slot if req.slot is not None
                                  else -1),
                            seq=len(req.tokens),
                            meta={"src": int(src)})
                    break               # placed, or no free slot there
                except PoolExhausted:
                    break               # full pool: try the next replica
                except CorruptionDetected:
                    # the payload failed its checksum BEFORE install
                    # (ISSUE 13): nothing was committed on the decode
                    # side, and the request is untouched on the
                    # PREFILL replica — it simply keeps decoding there,
                    # token-identically (the handoff is opportunistic).
                    # The corrupt payload dies with this attempt: it is
                    # never offered to another replica.
                    self.handoff_corruptions_total += 1
                    _obs.serving_integrity("handoff", "detected")
                    _obs.serving_integrity("handoff", "quarantined")
                    return
                except EngineDead:
                    self._failover(didx)
                    break
                except StepStalled as exc:
                    # a TIMED-OUT import is NOT retryable in place:
                    # the abandoned watchdog thread may still complete
                    # the original install, so a retry could run
                    # concurrently and double-install. Charge the
                    # replica a recovery instead — the rebuild fences
                    # the poisoned engine (slot tables cleared), so a
                    # late-completing import commits into a discarded
                    # engine, never a live one.
                    try:
                        dsup._on_failure(exc)
                    except EngineDead:
                        self._failover(didx)
                    break
                except Exception as exc:  # noqa: BLE001 — transient or
                    # real fault inside the DECODE-side import
                    # (allocator, scatter, injected). First the
                    # bounded idempotent retry (a failed import frees
                    # everything it allocated before re-raising, and
                    # journal ownership moves only at adopt_running —
                    # so a retry can never double-install pages or
                    # double-own recovery); past the budget it is that
                    # replica's failure: its supervisor pays the
                    # recovery and its circuit counts it — never the
                    # healthy prefill replica's.
                    attempts += 1
                    if attempts <= self.handoff_retries:
                        self.handoff_retries_total += 1
                        _obs.serving_integrity_retry("handoff_import")
                        self._retry_sleep(
                            min(0.2, 0.005 * 2 ** (attempts - 1)))
                        continue
                    try:
                        dsup._on_failure(exc)
                    except EngineDead:
                        self._failover(didx)
                    break
            if placed is not None:
                break
        if placed is None:
            return                      # keep decoding on the prefill side
        dsup = self.replicas[placed]
        dsup.adopt_running(req)
        self._owner[req.rid] = placed
        sup.journal.forget(req.rid)
        eng.finish_handoff(req, payload["slot"])
        self.handoffs_total += 1

    # ---- failover / rolling upgrade ----
    def _rehome(self, entries):
        """Re-dispatch journaled sessions from a dead/retiring replica:
        in-flight ones re-enter elsewhere with resume semantics (the
        PR 4 replay — token-identical), never-admitted ones go back
        through the router queue as fresh work."""
        rehomed = 0
        for e in entries:
            req = e.req
            if req is None or (req.done
                               and req.finish_reason != "engine_dead"):
                continue
            req.done = False
            req.slot = None
            req.tokens = list(e.tokens)
            if e.admitted:
                req.preemptions = e.preemptions + 1
                req.finish_reason = FinishReason.PREEMPTED.value
                loads = self._alive(self._decode_idxs()) or self._alive(
                    range(len(self.replicas)))
                idx, _ = self.router.pick_replica(None, loads)
                _obs.serving_trace_mark(req, "rehome", replica=idx,
                                        seq=len(req.tokens))
                self.replicas[idx].submit_request(req)
                self.router.note_dispatch(idx, False)
                self._owner[req.rid] = idx
            else:
                req.finish_reason = None
                meta = self._meta.get(req.rid, {"tenant": "default",
                                                "cost": 0})
                self._rq.append({"req": req, "tenant": meta["tenant"],
                                 "cost": meta["cost"],
                                 "seq": self._seq})
                self._seq += 1
            rehomed += 1
        _obs.serving_router_failover(rehomed)
        return rehomed

    def _failover(self, idx: int):
        """A replica's circuit opened: rebuild it in place (fresh
        pools, empty trie — its affinity bindings drop) and rehome its
        journaled sessions onto the survivors. Requests the dying
        supervisor marked ``engine_dead`` un-finish and resume
        elsewhere — cluster-wide, nothing is lost."""
        dead = self.replicas[idx]
        self.failovers_total += 1
        entries = dead.journal.live_entries()
        if dead.wal is not None:
            # ownership moves with the rehome: tombstone every live
            # session in the DEAD replica's journal directory (and
            # fsync + close it) BEFORE the replacement adopts the dir —
            # a later cold recovery of this directory must not
            # resurrect sessions the survivors are already serving,
            # and two writers must never interleave frames in one file
            try:
                for e in entries:
                    dead.journal.forget(e.rid)
                dead.wal.commit(force=True)
            except Exception:
                pass    # best-effort: cold recovery dedupes by rid
            dead.wal.close()
        self.replicas[idx] = self._new_supervisor(idx)
        self.router.drop_replica(idx)
        self._rehome(entries)

    def retire_replica(self, idx: int, *, path: Optional[str] = None,
                       replace: bool = True) -> Dict:
        """Rolling drain/upgrade: drain replica ``idx`` through the
        PR 8 drain path (journal + prefix-trie checkpoint to one
        ``.npz``), requeue its live sessions onto other replicas
        MID-DECODE (resume semantics — they finish token-identically),
        and — with ``replace`` — install a fresh replica with the
        drained prefix trie restored, so the tenant's next prompt still
        prefix-HITs and the router's affinity bindings stay valid.
        Returns the drain summary."""
        if not replace:
            # count SERVICEABLE survivors, not list length — drained
            # husks stay in self.replicas, so repeated non-replace
            # retirements would otherwise drain the whole cluster
            # through this guard one replica at a time
            survivors = [i for i, s in enumerate(self.replicas)
                         if i != idx and s.health != "dead"
                         and not s._draining]
            if not survivors:
                raise ValueError(
                    "retire_replica(replace=False) would leave no "
                    "serviceable replica — nothing left to serve or "
                    "absorb the drained sessions")
        sup = self.replicas[idx]
        tmp = None
        if path is None:
            fd, tmp = tempfile.mkstemp(suffix=".npz",
                                       prefix="retire_replica_")
            os.close(fd)
            path = tmp
        try:
            summary = sup.drain(path)
            entries = sup.journal.live_entries()
            if replace:
                new = self._new_supervisor(idx)
                ckpt = load_drain_checkpoint(path)
                if ckpt["prefix"] is not None:
                    new.engine.cache.restore_prefix(ckpt["prefix"])
                self.replicas[idx] = new
            else:
                self.router.drop_replica(idx)
            summary["rehomed"] = self._rehome(entries)
            self.retirements_total += 1
            return summary
        finally:
            if tmp is not None and os.path.exists(tmp):
                os.unlink(tmp)

    # ---- whole-process cold recovery (ISSUE 15) ----
    @classmethod
    def recover_from_disk(cls, engine_factory: Callable,
                          wal_dir: str, *, replicas: Optional[int] = None,
                          **kw) -> "ServingCluster":
        """Rebuild a cluster after WHOLE-PROCESS death from its
        per-replica journal directories: each ``replica<i>/`` WAL
        recovers into replica ``i``
        (:meth:`~paddle_tpu.serving.EngineSupervisor.recover_from_disk`
        — torn tails truncated, checkpoints + log suffixes replayed),
        sessions that a crash caught MID-HANDOFF (adopted on the
        decode side, not yet tombstoned on the prefill side) dedupe by
        rid — the copy with more committed tokens wins, the loser is
        durably forgotten — and every recovered handle re-enters the
        cluster's owner map so :meth:`step`/:meth:`run` drive it to
        completion. Recovered handles live in ``.recovered``
        (rid → request)."""
        sub = sorted(d for d in (os.listdir(wal_dir)
                                 if os.path.isdir(wal_dir) else ())
                     if d.startswith("replica"))
        n = replicas if replicas is not None else max(len(sub), 1)
        cluster = cls(engine_factory, replicas=n, wal_dir=wal_dir,
                      _recover=True, **kw)
        cluster.recovered: Dict[int, object] = {}
        best: Dict[int, tuple] = {}     # rid -> (idx, req)
        for i, sup in enumerate(cluster.replicas):
            for rid, req in getattr(sup, "restored", {}).items():
                prev = best.get(rid)
                if prev is None:
                    best[rid] = (i, req)
                    continue
                # mid-handoff duplicate: keep the furthest-along copy
                # (the adopt side committed at least as many tokens);
                # the loser forgets durably so the NEXT cold recovery
                # of that directory is already clean
                keep_new = len(req.tokens) > len(prev[1].tokens)
                (lose_i, lose_req) = prev if keep_new else (i, req)
                if keep_new:
                    best[rid] = (i, req)
                loser = cluster.replicas[lose_i]
                loser.journal.forget(rid)
                loser.engine.cancel_request(lose_req, "superseded")
        for rid, (idx, req) in best.items():
            cluster._live[rid] = req
            cluster._owner[rid] = idx
            cluster._meta[rid] = {"tenant": "default",
                                  "cost": req.prompt.shape[1]
                                  + req.max_new_tokens}
            cluster.recovered[rid] = req
            cluster._next_rid = max(cluster._next_rid, rid + 1)
        return cluster

    # ---- introspection ----
    def stats(self) -> Dict:
        per = []
        for i, sup in enumerate(self.replicas):
            s = sup.load_stats()
            s["role"] = ("prefill" if i < self.prefill_replicas
                         else "decode")
            per.append(s)
        return {
            "replicas": len(self.replicas),
            "replicas_serviceable": len(
                self._alive(range(len(self.replicas)))),
            "prefill_replicas": self.prefill_replicas,
            "cluster_steps": self._steps,
            "router_queued": len(self._rq),
            "handoffs_total": self.handoffs_total,
            "handoff_retries_total": self.handoff_retries_total,
            "handoff_corruptions_total": self.handoff_corruptions_total,
            "autoscale_faults_total": self.autoscale_faults_total,
            "failovers_total": self.failovers_total,
            "retirements_total": self.retirements_total,
            "deadline_cancels_total": self.deadline_cancels_total,
            "router": self.router.stats(),
            "per_replica": per,
            **({"autoscaler": self.autoscaler.stats()}
               if self.autoscaler is not None else {}),
            **({"host_tier": self._host_store.stats()}
               if self._host_store is not None else {}),
        }
