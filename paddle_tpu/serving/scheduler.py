"""SLO-aware serving scheduler: the control plane over the
continuous-batching engine.

PRs 2–3 built the data plane — paged KV pool, refcounted prefix cache,
chunked prefill, one static-shape ragged decode program — but admission
stayed FIFO and best-effort: a burst of long prompts starves in-flight
decodes, and under :class:`~paddle_tpu.serving.PoolExhausted` the engine
can only back-pressure, never reclaim. :class:`ServingScheduler` closes
that gap (design shape: Orca/vLLM-style schedulers on page-granular
preemption):

- **Priority queues** — requests carry a priority class
  (:class:`~paddle_tpu.serving.policy.Priority`; lower = more
  important) and admit strictly by class, FIFO within a class.
- **Token-budgeted step planning** — per step a
  :class:`~paddle_tpu.serving.policy.TokenBudgetPlanner` packs decode
  slots (1 token each) and prefill chunks (page-rounded widths) in
  priority order under ``token_budget``, bounding the latency of every
  engine step; ready work the budget defers runs on later steps.
- **Preempt / resume over paged KV** — when a higher-priority admission
  cannot be satisfied, a
  :class:`~paddle_tpu.serving.policy.PreemptionPolicy` victim's pages
  are evicted back to the pool
  (:meth:`~paddle_tpu.serving.PagedKVCache.evict_for_preempt`; pages
  shared with the prefix trie survive under the trie's references and
  reclaim via the allocator's evict-on-pressure path) and the victim
  requeues at the FRONT of its class. Resume replays ``prompt +
  tokens[:-1]`` through the PR-3 continuation-prefill program
  (:func:`~paddle_tpu.models.generate.paged_prefill_chunk`) — prefix
  pages still in the trie map straight back in — and continues decoding
  from the last sampled token, TOKEN-IDENTICAL to an uninterrupted run
  (gated in ``tests/test_scheduler.py`` at fp and int8-KV).
- **Deadlines** — a queued request whose ``deadline_s`` lapses before
  admission is cancelled with the structured finish reason
  ``deadline_exceeded`` instead of silently aging in the queue. The
  deadline is an ADMISSION SLO: a request that was admitted in time
  and later preempted already met it, so preempted requeues resume
  instead of being cancelled.

Telemetry (paddle_tpu.observability): per-class queue-depth gauges,
preemption/resume counters, a time-in-queue histogram, and a per-step
budget-utilization gauge — zero-cost when metrics are disabled.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, Optional

import numpy as np

from ..observability import hooks as _obs
from .adapters import AdapterPoolExhausted
from .paged_cache import PoolExhausted
from .policy import (FinishReason, PreemptionPolicy, Priority, StepPlan,
                     TokenBudgetPlanner)
from .resilience import DEGRADED_MODES, fault_point


class ServingScheduler:
    """Request-lifecycle scheduler between callers and a
    :class:`~paddle_tpu.inference.ContinuousBatchingEngine`.

    The scheduler OWNS the engine: callers submit through
    :meth:`submit` (never ``engine.submit``) and drive :meth:`step` /
    :meth:`run`; the engine's own FIFO queue stays empty. ``clock`` is
    injectable (monotonic seconds) so deadline behavior is testable.
    """

    def __init__(self, engine, *, token_budget: Optional[int] = None,
                 enable_preemption: bool = True,
                 planner: Optional[TokenBudgetPlanner] = None,
                 preemption_policy: Optional[PreemptionPolicy] = None,
                 clock: Callable[[], float] = time.monotonic,
                 mesh=None, overlap: Optional[bool] = None):
        if not engine.idle:
            raise ValueError(
                "ServingScheduler requires a fresh engine: it owns "
                "admission, and requests already queued or running "
                "through the engine's FIFO path would bypass priority")
        if mesh is not None and getattr(engine, "mesh", None) is not mesh:
            # the scheduler is pure host logic and shards NOTHING
            # itself — the tensor-parallel data plane lives in the
            # engine (ISSUE 7). The knob exists so a deployment that
            # wires the mesh at the scheduler surface fails loudly on a
            # mismatch instead of silently scheduling a single-chip
            # engine it believed was sharded.
            raise ValueError(
                "ServingScheduler(mesh=...) does not match the "
                "engine's mesh — pass the mesh to "
                "ContinuousBatchingEngine(mesh=...); the scheduler's "
                "host logic is mesh-agnostic (identical plans, "
                "replicated block tables)")
        self.mesh = mesh if mesh is not None else getattr(
            engine, "mesh", None)
        self.engine = engine
        self.planner = planner or TokenBudgetPlanner(
            token_budget, engine.cache.page_size)
        self.preemption = (preemption_policy or PreemptionPolicy()
                           if enable_preemption else None)
        self.clock = clock
        self._queues: Dict[int, Deque] = {}
        self._drafts: Dict = {}      # this step's speculative proposals
        self.last_plan: Optional[StepPlan] = None
        self._steps = 0
        self.preemptions_total = 0
        self.resumes_total = 0
        self.deadline_cancels_total = 0
        self._swap_debt = 0     # host-tier swap-in tokens not yet charged
        # the engine's degraded-mode rung, mirrored here by whoever
        # owns the ladder (EngineSupervisor._apply_degraded) so
        # load_stats() is a complete health snapshot — previously the
        # rung was only observable through the metrics registry, which
        # a router cannot read when metrics are disabled
        self.degraded_level = 0
        # --- async overlapped runtime (ISSUE 12): overlap=True turns
        # step() into the double-buffered pipeline — expire/admit/plan
        # step N+1 WHILE step N's decode/verify program runs on device,
        # commit step N (the single host fetch + bookkeeping) only when
        # its result is needed (just before step N+1's dispatch), then
        # dispatch N+1 and return with it in flight. None inherits the
        # engine's own knob; False is the synchronous bit-identity
        # reference the overlapped path is gated against.
        self.overlap = bool(getattr(engine, "overlap", False)
                            if overlap is None else overlap)
        # deadline fast path: _expire_deadlines scans every queue each
        # step — pointless host work when no live request ever carried
        # a deadline (the common case); one counter skips it
        self._deadlines_live = 0
        #: committed units (tokens/slots) of the last step — the
        #: busy-spin detector's input alongside last_plan
        self.last_committed = 0
        #: host-overhead telemetry mirrors (readable without the
        #: metrics registry — the bench rider's source): fraction of
        #: the last step's wall time spent on EXPOSED host work (host
        #: bookkeeping not hidden under an in-flight device program)
        self.last_host_frac: Optional[float] = None
        self.host_frac_ema: Optional[float] = None
        self.idle_fences_total = 0

    # ---- identity (ISSUE 16) ----
    @property
    def replica_id(self) -> int:
        """The replica id trace spans carry — one source of truth (the
        engine's), stamped by the cluster/supervisor; -1 = unplaced."""
        return getattr(self.engine, "replica_id", -1)

    @replica_id.setter
    def replica_id(self, value: int) -> None:
        self.engine.replica_id = int(value)

    # ---- intake ----
    def submit(self, prompt, max_new_tokens: int = 16, *,
               priority=Priority.NORMAL,
               deadline_s: Optional[float] = None, eos_token_id=None,
               adapter_id: int = 0, constraint=None):
        """Queue a prompt with a priority class and an optional
        admission deadline (seconds from now; a request still queued
        when it lapses is cancelled with ``deadline_exceeded``).
        Returns the request handle (``.done`` / ``.tokens`` /
        ``.output`` / ``.finish_reason`` fill in as steps run).

        ``adapter_id`` / ``constraint`` (ISSUE 14) pass through to the
        engine's request intake; an admission whose adapter slot pool
        is fully pinned defers exactly like one the page pool can't
        cover (:class:`~paddle_tpu.serving.adapters.
        AdapterPoolExhausted` is a :class:`PoolExhausted`)."""
        req = self.engine.create_request(
            prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, adapter_id=adapter_id,
            constraint=constraint)
        req.priority = int(priority)
        req.submitted_at = req.enqueued_at = self.clock()
        if deadline_s is not None:
            req.deadline_at = req.submitted_at + float(deadline_s)
            self._deadlines_live += 1
        # trace minted HERE (ISSUE 16): it rides the handle through
        # every lifecycle edge from this point on
        _obs.serving_trace_submit(req, replica=self.replica_id)
        _obs.serving_trace_enqueued(req)
        self._queues.setdefault(int(priority), deque()).append(req)
        return req

    def requeue(self, req, *, front: bool = False):
        """Re-enqueue an EXISTING request handle into its priority
        class — the supervisor's recovery/restore path
        (:class:`~paddle_tpu.serving.resilience.EngineSupervisor`
        re-seats journaled sessions through the normal admission
        machinery so the resume replay stays the one gated code path).
        ``front`` requeues ahead of the class (a preemption-style
        requeue)."""
        req.enqueued_at = self.clock()
        if req.submitted_at is None:
            req.submitted_at = req.enqueued_at
        if req.deadline_at is not None:
            self._deadlines_live += 1
        # attach is idempotent: a handle that already rides a trace
        # (handoff import, failover rehome) keeps it — stitching; a
        # recovered handle minted fresh gets one here
        _obs.serving_trace_submit(req, replica=self.replica_id)
        _obs.serving_trace_enqueued(req)
        q = self._queues.setdefault(int(req.priority), deque())
        if front:
            q.appendleft(req)
        else:
            q.append(req)

    # ---- per-step phases ----
    def _expire_deadlines(self, now: float):
        """Cancel requests whose deadline lapsed before they produced a
        token. The deadline is a FIRST-TOKEN SLO in two phases:

        - QUEUED requests that lapse cancel with ``deadline_exceeded``
          (never admitted, never held pages).
        - MID-PREFILL admissions that lapse cancel BEFORE their next
          chunk is planned, releasing their reserved pages back to the
          pool (previously expiry only fired between queue scans, so a
          long chunked prefill kept burning budget and pages for a
          request that could never meet its SLO). Pages shared with the
          prefix trie survive under the trie's references, exactly as
          on any retirement.

        A request the scheduler admitted in time and then preempted
        (``preemptions > 0``) already met the SLO — cancelling would
        discard finished work because of the scheduler's own eviction,
        so preempted requeues (and their resume replays) are exempt and
        simply resume."""
        if not self._deadlines_live:
            # vectorized-bookkeeping fast path (ISSUE 12 satellite c):
            # no deadline-bearing request was ever (re)enqueued, so the
            # per-queue scans below can never find work — skip the
            # whole pass instead of walking every queue every step
            return

        def expired(r):
            return (r.deadline_at is not None and now >= r.deadline_at
                    and r.preemptions == 0)
        for prio, q in self._queues.items():
            if not any(expired(r) for r in q):
                continue
            keep: Deque = deque()
            for req in q:
                if expired(req):
                    self.engine.cancel_request(
                        req, FinishReason.DEADLINE_EXCEEDED.value)
                    self.deadline_cancels_total += 1
                else:
                    keep.append(req)
            self._queues[prio] = keep
        # mid-prefill expiry (ISSUE 8 satellite): tokens are only
        # sampled once prefill completes, so a pending admission past
        # its deadline has produced nothing worth keeping — cancel it
        # and free its reserved pages before planning its next chunk
        for slot, (req, _rem) in list(
                self.engine.pending_prefills().items()):
            if expired(req) and not req.tokens:
                self.engine.cancel_request(
                    req, FinishReason.DEADLINE_EXCEEDED.value)
                self.deadline_cancels_total += 1

    def _preempt_for(self, req, candidates=None) -> bool:
        """Evict one strictly-lower-class running request to make room
        for ``req``; the victim requeues at the FRONT of its class (it
        already waited its turn once). Under the host tier (ISSUE 10)
        the policy PREFERS victims whose eviction swaps to host RAM
        (near-free swap-in resume) over mid-prefill victims that would
        pay a replay. ``candidates`` restricts the victim set (the
        adapter-slot shortfall path: only victims that pin a slot can
        relieve it). Returns False when no eligible victim exists."""
        if self.preemption is None:
            return False
        running = self.engine.running_requests()
        if candidates is not None:
            running = [r for r in running if r in candidates]
        victim = self.preemption.pick_victim(
            running, req.priority,
            swappable=getattr(self.engine, "swap_candidate", None))
        if victim is None:
            return False
        self.engine.preempt_request(victim)
        self.preemptions_total += 1
        victim.enqueued_at = self.clock()   # queue wait restarts here
        _obs.serving_trace_enqueued(victim)
        self._queues.setdefault(int(victim.priority),
                                deque()).appendleft(victim)
        return True

    def _preemption_feasible(self, req) -> bool:
        """Optimistic feasibility bound before evicting ANYONE for a
        pool shortfall: every usable page not pinned by an
        equal-or-higher-class table is reclaimable in principle (free
        pages, strictly-lower-class victims' pages, trie-held pages —
        the allocator's evict-on-pressure path reaches the last). If
        even that bound can't cover the request, preempting would cost
        each victim an eviction + full resume replay and the admission
        would STILL fail — bail out with zero casualties instead."""
        cache = self.engine.cache
        pinned = set()
        for r in self.engine.running_requests():
            if r.priority <= int(req.priority):
                pinned.update(cache.pages_held(r.slot))
        need = cache.pages_for(req.prompt.shape[1] + req.max_new_tokens)
        return need <= cache.allocator.num_usable - len(pinned)

    def _adapter_feasible(self, req) -> bool:
        """Can ``req``'s adapter be seated AT ALL right now? False
        when the pool needs a new slot, none is free or reclaimable,
        and no strictly-lower-class running request pins one — in that
        state every preemption (seat- or page-motivated) is pointless,
        so the admission defers with zero casualties."""
        aid = getattr(req, "adapter_id", 0)
        pool = getattr(self.engine, "adapters", None)
        if not aid or pool is None or pool.resident(aid):
            return True                 # base row / pin-in-place hit
        if pool.slot_available():
            return True
        return any(getattr(r, "adapter_id", 0) != 0
                   and int(r.priority) > int(req.priority)
                   for r in self.engine.running_requests())

    def _admit_one(self, req) -> bool:
        eng = self.engine
        while True:
            if not self._adapter_feasible(req):
                return False
            if not eng.cache.free_slots():
                # no slot: preempt only when the POOL side can work out
                # too (feasibility), else the victim pays for nothing
                if not (self._preemption_feasible(req)
                        and self._preempt_for(req)):
                    return False
                continue                # preemption freed a slot; retry
            try:
                return eng.admit_request(req)
            except AdapterPoolExhausted:
                # every ADAPTER slot is pinned: page reclaim cannot
                # help, so only a strictly-lower-class victim that
                # itself pins a slot is worth evicting — with none,
                # defer (back-pressure) instead of thrashing base-model
                # victims whose preemption frees no adapter slot
                pinning = [r for r in eng.running_requests()
                           if getattr(r, "adapter_id", 0) != 0]
                if not (pinning
                        and self._preempt_for(req, candidates=pinning)):
                    return False
            except PoolExhausted:
                # a slot is free but the POOL can't cover the request:
                # evict a lower-class victim's pages and retry. Each
                # round removes one running request, so this terminates.
                if not (self._preemption_feasible(req)
                        and self._preempt_for(req)):
                    return False

    def _admit(self, now: float):
        """Admit strictly by class (FIFO within a class). A blocked
        head-of-class blocks everything below it — admitting a smaller
        lower-class request around a starved higher-class one would be
        priority inversion by another name."""
        for prio in sorted(self._queues):
            q = self._queues[prio]
            while q:
                req = q[0]
                if req.done:
                    # cancelled while queued (e.g. a caller's direct
                    # engine.cancel_request): admitting would decode it
                    # anyway and overwrite the cancellation
                    q.popleft()
                    continue
                if not self._admit_one(req):
                    return
                q.popleft()
                if req.preemptions > 0:
                    self.resumes_total += 1
                # time-in-queue since the LATEST enqueue: a resumed
                # request's prior running time is not queue wait. The
                # clamp covers a victim preempted and re-admitted
                # within this same pass (its requeue stamp postdates
                # ``now``) — that wait is zero, not negative.
                _obs.serving_queue_wait(
                    max(0.0, now - req.enqueued_at), prio)

    def _plan(self, reserved: int = 0) -> StepPlan:
        eng = self.engine
        ready = eng.ready_mask()
        decode = [(r.priority, r.rid, r.slot)
                  for r in eng.running_requests() if ready[r.slot]]
        pending = [(req.priority, req.rid, slot, remaining)
                   for slot, (req, remaining)
                   in eng.pending_prefills().items()]
        # speculative engines draft at PLAN time so each row's verify
        # width (1 + drafts) is charged against the budget before
        # anything executes; the proposals are stashed for this step's
        # execution (the engine must not re-propose under a different
        # history). The OVERLAPPED pipeline plans before the previous
        # step commits — the history the proposer needs is not final —
        # so it charges the pessimistic per-row width instead
        # (spec_plan_widths) and proposes real drafts post-commit,
        # trimmed to the planned allowance (the budget stays a hard
        # ceiling either way).
        if getattr(eng, "spec", None) is None:
            self._drafts = {}
            widths = None
        elif self.overlap:
            self._drafts = None
            widths = eng.spec_plan_widths(ready) or None
        else:
            self._drafts = eng.propose_drafts(ready)
            widths = {s: d.size for s, d in self._drafts.items()} or None
        # 2-D serving mesh (ISSUE 17): slots split into contiguous
        # per-dp-shard row blocks, and the step's wall time is the max
        # over shards — tell the planner which block each slot rides
        # so a budget-truncated decode set spreads across shards
        dpg = None
        if int(getattr(eng, "dp", 1) or 1) > 1:
            rows = eng.max_batch // eng.dp
            dpg = {s: s // rows for s in range(eng.max_batch)}
        return self.planner.plan(
            decode, pending, chunk_cap=eng.prefill_chunk,
            spec_drafts=widths, reserved_tokens=reserved, dp_group=dpg)

    def _trim_plan(self, plan: StepPlan) -> StepPlan:
        """Reconcile an overlap-mode plan with the commit that just
        landed: the plan was drawn against the PREDICTED post-commit
        state, so slots whose request finished (eos at commit), was
        preempted, or whose prefill completed are dropped. Trimming
        only ever REMOVES work, so the budget ceiling the plan was
        packed under still holds; per-request output is unaffected
        (greedy decode is batch-composition independent — the standing
        parity gates)."""
        eng = self.engine

        def alive(s):
            req = eng._slots[s]
            return (req is not None and not req.done
                    and s not in eng._pending)
        plan.decode_slots = [s for s in plan.decode_slots if alive(s)]
        if plan.spec_drafts:
            keep = set(plan.decode_slots)
            plan.spec_drafts = {s: k for s, k in plan.spec_drafts.items()
                                if s in keep}
        plan.prefills = [(s, c) for s, c in plan.prefills
                         if s in eng._pending]
        return plan

    def _dispatch_plan(self, plan: StepPlan) -> None:
        """Launch the plan's programs WITHOUT committing: prefill
        chunks first (the decode program chains behind them on
        device), then the masked decode/verify step. Speculative rows
        propose their REAL drafts here — post-commit, so the history
        is final — trimmed to the planner's per-row allowance."""
        eng = self.engine
        for slot, cap in plan.prefills:
            eng.prefill_dispatch(slot, max_tokens=cap)
        if not plan.decode_slots:
            return
        mask = np.zeros((eng.max_batch,), bool)
        mask[plan.decode_slots] = True
        if plan.spec_drafts and getattr(eng, "spec", None) is not None:
            fresh = eng.propose_drafts(mask)
            eng.spec_dispatch(mask, {
                s: fresh[s][:k] for s, k in plan.spec_drafts.items()
                if s in fresh})
        else:
            eng.decode_dispatch(mask)

    def _execute_plan(self, plan: StepPlan) -> int:
        """The synchronous reference execution: each program dispatches
        and commits in place (prefill chunks, then the masked
        decode/verify program). Returns committed units."""
        eng = self.engine
        n = 0
        for slot, cap in plan.prefills:
            eng.prefill_step(slot, max_tokens=cap)
            n += 1
        if plan.decode_slots:
            mask = np.zeros((eng.max_batch,), bool)
            mask[plan.decode_slots] = True
            if plan.spec_drafts:
                # execute the budgeted verify: proposals trimmed to the
                # planner's per-row draft allowance (a row the budget
                # degraded to plain decode rides the verify batch with
                # zero drafts — it commits exactly its greedy token)
                n += eng.spec_step(mask, {
                    s: self._drafts[s][:k]
                    for s, k in plan.spec_drafts.items()})
            else:
                n += eng.decode_step(mask)
        return n

    def step(self) -> bool:
        """One scheduler step: expire deadlines, admit (preempting if
        needed), plan under the token budget, then execute. With
        ``overlap=False`` execution is the synchronous chain (prefill
        chunks, then the masked decode program, each committed in
        place). With ``overlap=True`` the step is DOUBLE-BUFFERED: the
        host phases above run while the PREVIOUS step's programs are
        still in flight on device; that step commits only once its
        result is actually needed (just before this step's dispatch),
        the plan is trimmed against what the commit changed, and this
        step's programs dispatch and are left in flight. Returns False
        when no work remains (the overlapped path drains its last
        in-flight step before saying so). ``last_plan`` holds the
        step's :class:`~paddle_tpu.serving.policy.StepPlan`."""
        fault_point("sched_tick")
        eng = self.engine
        if eng.queued_requests():
            # engine.submit() after attach would sit in the engine's
            # FIFO queue forever (the scheduler only drains its own
            # priority queues) — step() would spin reporting work
            # remains while never decoding it. Fail loudly instead.
            raise ValueError(
                "requests were queued through engine.submit() after "
                "the scheduler attached — submit through "
                "ServingScheduler.submit so priority admission is "
                "not bypassed")
        t_wall0 = time.perf_counter_ns()
        # host work done while a previous step is in flight on device
        # is HIDDEN (off the critical path); the same work with the
        # device idle is EXPOSED — the host_overhead_fraction gauge's
        # numerator. The synchronous path never overlaps, so all its
        # host time is exposed by construction.
        hidden = self.overlap and eng.has_inflight()
        eng.take_fence_ns()                 # reset the device-wait tally
        now = self.clock()
        self._expire_deadlines(now)
        self._admit(now)
        # host tier (ISSUE 10): admissions that SWAPPED IN during
        # _admit already wrote KV bytes this step (one scatter per
        # resume) — charge them against the step budget at the prefill
        # rate (page_size tokens per page). A single swap-in larger
        # than the whole budget AMORTIZES: the debt carries into later
        # steps' reserves, so every step's (planned + reserved) stays
        # under the ceiling and the average per-step KV-write bound
        # the budget promises holds through swap-heavy bursts.
        consume = getattr(eng.cache, "consume_swap_charge", None)
        if consume is not None:
            self._swap_debt += consume()
        budget = self.planner.token_budget
        reserved = (min(self._swap_debt, budget) if budget
                    else self._swap_debt)
        self._swap_debt -= reserved
        plan = self._plan(reserved)
        t_planned = time.perf_counter_ns()
        if self.overlap:
            # the ONE commit fence: step N's result is needed now —
            # its sampled tokens seed step N+1's dispatch inputs
            committed = eng.commit_inflight()
            plan = self._trim_plan(plan)
            self._dispatch_plan(plan)
        else:
            committed = self._execute_plan(plan)
        self.last_plan = plan
        self.last_committed = committed
        self._steps += 1
        t_end = time.perf_counter_ns()
        wall = max(1, t_end - t_wall0)
        exposed = max(0, (t_end - t_wall0) - eng.take_fence_ns()
                      - ((t_planned - t_wall0) if hidden else 0))
        frac = min(1.0, exposed / wall)
        self.last_host_frac = frac
        self.host_frac_ema = (frac if self.host_frac_ema is None
                              else 0.9 * self.host_frac_ema + 0.1 * frac)
        _obs.serving_sched_step(
            {p: len(q) for p, q in self._queues.items()},
            # swap-in reserves are spent budget: the utilization gauge
            # reports what the step actually consumed, plan + reserve
            plan.scheduled_tokens + plan.reserved_tokens, plan.budget)
        _obs.serving_overlap_step(exposed, wall, committed, self.overlap)
        return (any(self._queues.values()) or not eng.idle
                or eng.has_inflight())

    def _idle_fence(self) -> None:
        """The busy-spin fix (ISSUE 12 satellite): a step that planned
        nothing and committed nothing means every remaining obligation
        is waiting on device or swap completion — re-planning empty
        steps would burn host CPU re-scanning queues (visible as
        zero-token steps in ``serving_sched_step``). Instead: commit
        whatever is in flight (a real fence — the blocked work becomes
        plannable next step), else flush pending async swap-out DMAs,
        else yield the thread."""
        eng = self.engine
        self.idle_fences_total += 1
        fenced = False
        if eng.has_inflight():
            self.last_committed = eng.commit_inflight()
            fenced = True
        else:
            fence = getattr(eng.cache, "fence_swaps", None)
            if fence is not None and fence():
                fenced = True
            else:
                time.sleep(0)           # yield: no fence to make progress on
        _obs.serving_sched_idle(fenced)

    def run(self) -> None:
        """Drive steps until every submitted request finished (or was
        cancelled by its deadline). A step that planned zero tokens and
        committed nothing fences/yields instead of immediately
        re-planning (see :meth:`_idle_fence`)."""
        while self.step():
            plan = self.last_plan
            if (plan is not None and plan.scheduled_tokens == 0
                    and plan.reserved_tokens == 0
                    and self.last_committed == 0):
                self._idle_fence()

    def flush(self) -> int:
        """Commit any in-flight work immediately (the overlapped
        path's explicit fence for callers that need every committed
        token visible NOW — e.g. before reading ``req.tokens`` between
        steps). No-op on the synchronous path."""
        return self.engine.commit_inflight()

    def load_stats(self) -> Dict:
        """One structured load/health snapshot — the PUBLIC surface a
        multi-replica router reads (ISSUE 9): per-class queue depths,
        the tightest queued deadline's remaining slack, slot and page
        occupancy, and the degraded-mode rung. Everything here is host
        bookkeeping (no device sync); the router never reaches into
        engine internals."""
        now = self.clock()
        eng = self.engine
        alloc = eng.cache.allocator
        depths = {int(p): len(q) for p, q in self._queues.items() if q}
        slack = None
        # backlog in TOKENS (ISSUE 13): what the queued requests will
        # actually cost to serve — the autoscaler's scale signal and
        # the admission controller's TTFT-feasibility denominator
        # (request counts hide the long-prompt/short-prompt mix)
        queued_tokens = 0
        for q in self._queues.values():
            for r in q:
                if not r.done:
                    queued_tokens += (r.prompt.shape[1]
                                      + r.max_new_tokens
                                      - len(r.tokens))
                if r.deadline_at is not None and not r.done:
                    s = r.deadline_at - now
                    slack = s if slack is None else min(slack, s)
        inflight_tokens = int(sum(
            r.max_new_tokens - len(r.tokens)
            for r in eng.running_requests() if not r.done))
        level = self.degraded_level
        s = {
            "queue_depths": depths,
            "queued_total": sum(depths.values()),
            "queued_tokens": int(queued_tokens),
            "inflight_tokens": inflight_tokens,
            "running": len(eng.running_requests()),
            "pending_prefills": len(eng.pending_prefills()),
            "free_slots": len(eng.cache.free_slots()),
            "oldest_deadline_slack_s": slack,
            "pool_occupancy": alloc.utilization(),
            "pool_free_pages": alloc.num_free,
            "degraded_level": level,
            "degraded_mode": (DEGRADED_MODES[level]
                              if level < len(DEGRADED_MODES) else "dead"),
        }
        host = getattr(eng.cache, "host", None)
        if host is not None:
            # hierarchical KV (ISSUE 10): the host tier's residency is
            # part of a replica's load picture — a router can prefer
            # replicas with host headroom for swap-heavy tenants
            s["host_pool_pages"] = host.pages_resident
            s["host_pool_bytes"] = host.bytes_resident
        pool = getattr(eng, "adapters", None)
        if pool is not None:
            # adapter plane (ISSUE 14): slot headroom + residency — the
            # router's adapter-affinity tie-breaker signal (a replica
            # already holding a tenant's adapter serves it with zero
            # load/promote cost)
            s["adapter_slots_free"] = pool.slots - pool.used_slots
            s["adapter_slots_used"] = pool.used_slots
        return s

    def stats(self) -> Dict:
        s = self.engine.stats()
        s["sched_steps"] = self._steps
        s["sched_queued"] = {int(p): len(q)
                             for p, q in self._queues.items() if q}
        s["preemptions_total"] = self.preemptions_total
        s["resumes_total"] = self.resumes_total
        s["deadline_cancels_total"] = self.deadline_cancels_total
        s["overlap"] = self.overlap
        s["idle_fences_total"] = self.idle_fences_total
        if self.host_frac_ema is not None:
            s["host_overhead_fraction"] = round(self.host_frac_ema, 4)
        if self.last_plan is not None:
            s["last_step_tokens"] = self.last_plan.scheduled_tokens
            s["token_budget"] = self.last_plan.budget
        return s
