"""Fault-tolerant serving: fault injection, supervised recovery, and
drain/restore over the continuous-batching engine (ISSUE 8).

The PR 2–7 serving stack assumes every device step succeeds: one raised
exception, stalled transfer, or poisoned compile kills the engine and
every in-flight session with it. This module closes that gap with three
pieces, all HOST-side (no new device programs):

- :class:`FaultInjector` — a deterministic, seeded injector with NAMED
  sites threaded through the hot path (:data:`SITES`: allocator
  alloc/free, decode / prefill-chunk / verify step execution,
  device→host transfer, scheduler tick). Each firing can ``raise``,
  ``stall`` past a watchdog deadline, or model a detected-corruption
  (``corrupt``: the payload never commits — the checksum caught it).
  Hot paths call :func:`fault_point`; when no injector is installed the
  cost is one module-attribute read.

- :class:`EngineSupervisor` — wraps a fresh
  :class:`~paddle_tpu.inference.ContinuousBatchingEngine` (built by an
  ``engine_factory`` so it can be rebuilt from scratch) behind a
  :class:`~paddle_tpu.serving.ServingScheduler`, keeping a host-side
  write-ahead :class:`RequestJournal`: admission params are journaled at
  submit time (before anything executes) and every committed token after
  each successful step. On a failed — or watchdog-stalled — step the
  supervisor tears the poisoned engine down, rebuilds pools from
  scratch, and restores every in-flight session through the PR 4
  ``resume_sequence`` replay path, so recovery is TOKEN-IDENTICAL to an
  uninterrupted run at fp and int8-KV, including under tp sharding
  (gated in tests/test_resilience.py). Between "healthy" and "dead" sit
  bounded exponential-backoff retries, a circuit breaker on repeated
  failures, and a pressure-ordered DEGRADED-MODE ladder
  (:data:`DEGRADED_MODES`: disable spec decode → shrink the prefill
  chunk → shed LOW-priority admissions with a structured
  ``rejected_overload`` finish reason), published to the PR 1 metrics
  registry as the ``serving_degraded_mode`` gauge (the future router's
  replica-health signal).

- **drain/restore** — :meth:`EngineSupervisor.drain` stops admissions
  and checkpoints every in-flight session (journal records) PLUS the
  prefix-cache trie — structure AND page KV bytes
  (:meth:`~paddle_tpu.serving.PagedKVCache.checkpoint_prefix`) — to one
  ``.npz`` file; :meth:`EngineSupervisor.restore` rebuilds a fresh
  engine, writes the trie pages back into the new pool, and requeues
  the sessions — so shared system prompts survive restarts as prefix
  HITS (ROADMAP item 4's persistence ask) and interrupted decodes
  finish token-identically.

Recovery cost model: the journal replays ``prompt + tokens[:-1]``
through the continuation-prefill program — exactly the PR 4 resume
cost — so recovery time is proportional to RESIDENT tokens, not to the
wall-clock already served (PERF_NOTES "Fault-tolerant serving").

Determinism note: greedy decode (``temperature == 0``) is bit-identical
across recovery by construction (replay never re-samples). For sampled
decode the supervisor snapshots the engine's PRNG key at each step
commit, so the stream also survives recovery at STEP granularity; a
fault after an intra-step key split replays with the committed
snapshot (the failed attempt's split is discarded with the engine).

Stall caveat: a watchdog-stalled step's thread is abandoned with the
poisoned engine (its slot table is cleared as a best-effort fence). An
injected ``stall`` always raises when it wakes — it never commits. A
REAL stalled device program that later completes could still race a
token append; the journal is authoritative (recovery resets every
request to its journaled tokens), which bounds the damage to a
transiently wrong ``req.tokens`` tail on an already-poisoned handle.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

import numpy as np

from ..observability import hooks as _obs
from .policy import FinishReason, Priority

#: the named injection sites threaded through the serving hot path —
#: tools/check_instrumentation.py enforces that every name here has a
#: matching ``fault_point("<site>")`` call site (and therefore a
#: matching ``site=`` label on the serving_fault_* counters)
#: "dispatch" fires AFTER a decode/verify program launches (the
#: in-flight handle is lost with the fault — nothing committed, the
#: journal replays); "commit" fires at the top of the commit half,
#: before the device→host fetch — the two seams the overlapped
#: runtime (ISSUE 12) opens between launch and host-state commit
ENGINE_SITES = ("alloc", "free", "decode_step", "prefill_chunk",
                "verify_step", "transfer", "sched_tick", "swap_out",
                "swap_in", "dispatch", "commit",
                # adapter plane, ISSUE 14 — both fire BEFORE anything
                # installs: a fresh registry load / a host-store
                # promotion that faults commits nothing, and the
                # retried admission finds the same sources intact.
                # NB keep this comment paren-free: check_fault_sites
                # parses the tuple with a non-greedy paren match
                "adapter_load", "adapter_promote",
                # durable journal plane, ISSUE 15: wal_append fires
                # BEFORE a frame is written, wal_fsync before the
                # fsync, checkpoint_write before the checkpoint file —
                # none commits anything, and the crash-point sweep
                # kills the process after each and recovers from disk
                "wal_append", "wal_fsync", "checkpoint_write",
                # draft-model + tree speculation, ISSUE 20 — both fire
                # BEFORE any commit: draft_propose before the draft
                # model's catch-up/propose forwards touch its pool,
                # tree_verify before the one-forward tree verify
                # launches. Draft-pool state is disposable, so a fault
                # at either recovers by rebuilding it cold.
                # NB keep this comment paren-free: check_fault_sites
                # parses the tuple with a non-greedy paren match
                "draft_propose", "tree_verify")

#: cluster-plane sites (ISSUE 13): the prefill→decode handoff's two
#: byte-moving halves and the autoscaler's control tick. They only
#: execute inside a :class:`~paddle_tpu.serving.cluster.ServingCluster`
#: — the single-engine chaos soak covers :data:`ENGINE_SITES`, the
#: traffic soak (tools/chaos_soak.py --traffic) covers these
CLUSTER_SITES = ("handoff_export", "handoff_import", "autoscale_tick",
                 # multi-process plane, ISSUE 19 — all four fire BEFORE
                 # any commit: rpc_send before a frame hits the socket,
                 # rpc_recv before a reply is decoded, fabric_put before
                 # a payload ships to the fabric server, fabric_get
                 # before a fetched payload is verified or installed.
                 # NB keep this comment paren-free: check_fault_sites
                 # parses the tuple with a non-greedy paren match
                 "rpc_send", "rpc_recv", "fabric_put", "fabric_get")

SITES = ENGINE_SITES + CLUSTER_SITES

#: the pressure-ordered degraded-mode ladder (index == level): each
#: recovery escalates one rung, sustained healthy steps climb back down
DEGRADED_MODES = ("healthy", "no_spec", "small_chunks", "shed_low")


def _draft_identity(engine):
    """The journaled DRAFT-MODEL identity (ISSUE 20): draft-pool
    STATE is disposable — never checkpointed, never journaled — so
    recovery only needs ``[draft_layers]`` (linear draft) or
    ``[draft_layers, tree_width, tree_depth]`` (tree speculation) to
    prove the replacement engine re-drafts token-identically; the
    rebuilt pool then refills cold through the catch-up forward.
    ``None`` for engines without a draft model."""
    dl = getattr(engine, "draft_layers", None)
    if dl is None:
        return None
    tree = getattr(engine, "spec_tree", None)
    return [int(dl)] + ([int(tree[0]), int(tree[1])] if tree else [])


class InjectedFault(RuntimeError):
    """A fault fired by the :class:`FaultInjector` (``site`` / ``mode``
    carry the classification through to the supervisor's counters)."""

    def __init__(self, site: str, mode: str = "raise", detail: str = ""):
        self.site = site
        self.mode = mode
        super().__init__(
            f"injected {mode} fault at site {site!r}"
            + (f": {detail}" if detail else ""))


class CorruptionDetected(InjectedFault):
    """A byte payload failed its checksum verification BEFORE install
    (ISSUE 13: every exported payload — handoff export/import, host-tier
    swap, standing-store ``.npz`` — carries per-array CRCs that are
    verified before any scatter). The corrupted bytes are NEVER
    committed to host or device state, so the caller either quarantines
    the entry and falls back to the gated replay path (swap/prefix
    payloads) or keeps the request on its exporting replica (handoff).
    Also raised by the injector's corrupt-and-detect mode, which models
    the same detection without real bytes."""

    def __init__(self, site: str, detail: str = ""):
        super().__init__(site, "corrupt",
                         detail or "checksum mismatch on fetched "
                         "payload; data discarded before commit")


class StepStalled(RuntimeError):
    """The supervisor's watchdog gave up on a step that exceeded its
    deadline (a hung transfer / wedged device program)."""

    def __init__(self, seconds: float):
        self.site = "watchdog"
        self.mode = "stall"
        super().__init__(f"engine step exceeded the {seconds:.3f}s "
                         f"watchdog deadline")


class EngineDead(RuntimeError):
    """The circuit breaker opened: repeated step failures exhausted the
    recovery budget and the supervisor will not retry further."""


#: the installed injector — hot paths read this ONE module attribute;
#: None (the default) costs nothing beyond the read
_ACTIVE: Optional["FaultInjector"] = None


def fault_point(site: str) -> None:
    """Hot-path injection site: no-op unless a :class:`FaultInjector`
    is installed (:func:`install` / ``with injector:``)."""
    inj = _ACTIVE
    if inj is not None:
        inj.fire(site)


def tamper_point(site: str) -> bool:
    """Payload-corruption injection site (ISSUE 13): True when the
    installed injector has an armed TAMPER shot due at ``site`` — the
    caller then flips real bytes in the payload it is about to verify,
    so the CHECKSUM path (not the injector) raises
    :class:`CorruptionDetected`. Unlike :func:`fault_point` this never
    raises: the whole point is that detection happens downstream, in
    the verifier the tamper exists to exercise."""
    inj = _ACTIVE
    return inj is not None and inj.tamper(site)


def run_with_deadline(fn: Callable, seconds: Optional[float]):
    """Run ``fn()`` under a watchdog deadline (the
    :meth:`EngineSupervisor._guarded` pattern, reusable for the
    cluster's handoff imports — ISSUE 13): raises :class:`StepStalled`
    past ``seconds``; ``None`` runs inline. The abandoned thread is
    daemonic — same contract (and same caveat) as the supervisor's
    step watchdog."""
    if seconds is None:
        return fn()
    box: Dict = {}

    def run():
        try:
            box["r"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed below
            box["e"] = e

    t = threading.Thread(target=run, daemon=True,
                         name="deadline-guarded-call")
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise StepStalled(seconds)
    if "e" in box:
        raise box["e"]
    return box.get("r")


def install(injector: Optional["FaultInjector"]) -> None:
    """Install ``injector`` globally (``None`` uninstalls)."""
    global _ACTIVE
    _ACTIVE = injector


def uninstall() -> None:
    install(None)


class FaultInjector:
    """Deterministic, seeded fault source for the named serving sites.

    Two firing styles compose:

    - **armed** (on demand): :meth:`arm` schedules a fault on the n-th
      FUTURE call at a site — the unit tests' way of killing the engine
      at an exact point (e.g. mid-decode, during a spec-verify step).
    - **rate** (chaos): every :func:`fault_point` call at an enabled
      site draws from a seeded RNG; at most ``max_faults`` total fire.
      Same seed + same call sequence => same faults, every run.

    ``modes`` picks what a rate-fired fault does: ``"raise"`` (raise
    :class:`InjectedFault`), ``"stall"`` (sleep ``stall_s`` — past the
    supervisor's watchdog deadline — then raise, so a stalled site never
    commits), ``"corrupt"`` (raise :class:`CorruptionDetected`,
    modeling a checksum catching a corrupted transfer before commit).

    Every firing is counted per site (``fired``), logged
    (``log``: ``(site, mode, call_index)``) and emitted to the
    ``serving_fault_injected_total{site,mode}`` counter.
    """

    def __init__(self, seed: int = 0, rate: float = 0.0,
                 sites: Optional[List[str]] = None,
                 modes=("raise",), stall_s: float = 0.1,
                 max_faults: Optional[int] = None):
        bad = set(sites or ()) - set(SITES)
        if bad:
            raise ValueError(
                f"FaultInjector: unknown site(s) {sorted(bad)}; "
                f"valid sites: {SITES}")
        bad = set(modes) - {"raise", "stall", "corrupt"}
        if bad:
            raise ValueError(f"FaultInjector: unknown mode(s) "
                             f"{sorted(bad)}")
        self.rate = float(rate)
        self.sites = tuple(sites) if sites is not None else SITES
        self.modes = tuple(modes)
        self.stall_s = float(stall_s)
        self.max_faults = max_faults
        self._rng = np.random.RandomState(seed)
        self.calls: Dict[str, int] = {s: 0 for s in SITES}
        self.fired: Dict[str, int] = {s: 0 for s in SITES}
        self.fired_total = 0
        self.log: List[tuple] = []
        self._armed: Dict[str, List[tuple]] = {}
        # payload-corruption shots (ISSUE 13): consumed by
        # tamper_point(), never by fire() — a tamper must flow through
        # the caller's checksum verifier, not raise here
        self._tamper_armed: Dict[str, List[int]] = {}
        self.tamper_calls: Dict[str, int] = {s: 0 for s in SITES}
        # stalls in flight, not yet attributed by a supervisor: the
        # watchdog only ever sees a StepStalled, so the supervisor asks
        # the installed injector whether the stall was its own (keeps
        # the injected-vs-real counter split exact under chaos)
        self.pending_stalls: List[str] = []

    def arm(self, site: str, mode: str = "raise", nth: int = 1) -> None:
        """Schedule one fault on the ``nth`` future call at ``site``
        (1 = the very next call). Armed faults fire regardless of
        ``rate``/``max_faults`` — they are the on-demand kill switch."""
        if site not in SITES:
            raise ValueError(f"arm: unknown site {site!r}")
        self._armed.setdefault(site, []).append(
            (self.calls[site] + int(nth), mode))

    def arm_tamper(self, site: str, nth: int = 1) -> None:
        """Schedule one PAYLOAD CORRUPTION on the ``nth`` future
        :func:`tamper_point` visit at ``site`` (ISSUE 13): the hot path
        then flips real bytes in the payload it is about to verify, so
        the checksum — not the injector — detects the corruption. The
        end-to-end detect→quarantine→replay path is what gets
        exercised, which a raised :class:`CorruptionDetected` (the
        ``corrupt`` mode) cannot do."""
        if site not in SITES:
            raise ValueError(f"arm_tamper: unknown site {site!r}")
        self._tamper_armed.setdefault(site, []).append(
            self.tamper_calls[site] + int(nth))

    def tamper(self, site: str) -> bool:
        """One :func:`tamper_point` visit: True when an armed tamper
        shot is due — counted, logged and metered like any firing
        (mode ``"tamper"``), but the caller corrupts its own payload
        instead of this method raising."""
        self.tamper_calls[site] = n = self.tamper_calls[site] + 1
        armed = self._tamper_armed.get(site)
        if not armed:
            return False
        for i, target in enumerate(armed):
            if n >= target:
                del armed[i]
                self.fired[site] += 1
                self.fired_total += 1
                self.log.append((site, "tamper", n))
                _obs.serving_fault(site, "tamper", injected=True)
                return True
        return False

    def fire(self, site: str) -> None:
        """One hot-path visit to ``site``: decide (armed schedule, then
        seeded rate) and inject. Raises on injection; returns silently
        otherwise."""
        self.calls[site] = n = self.calls[site] + 1
        mode = None
        armed = self._armed.get(site)
        if armed:
            for i, (target, m) in enumerate(armed):
                if n >= target:
                    mode = m
                    del armed[i]
                    break
        if (mode is None and self.rate > 0.0 and site in self.sites
                and (self.max_faults is None
                     or self.fired_total < self.max_faults)
                and self._rng.random_sample() < self.rate):
            mode = self.modes[self._rng.randint(len(self.modes))]
        if mode is None:
            return
        self.fired[site] += 1
        self.fired_total += 1
        self.log.append((site, mode, n))
        _obs.serving_fault(site, mode, injected=True)
        if mode == "stall":
            # sleep past the supervisor's watchdog, then raise — the
            # stalled site never commits, so the abandoned step thread
            # cannot race the recovery that replaced it. Registered
            # BEFORE the sleep: the watchdog fires mid-sleep and the
            # supervisor attributes the StepStalled to this injection
            self.pending_stalls.append(site)
            time.sleep(self.stall_s)
            raise InjectedFault(site, "stall",
                                f"stalled {self.stall_s}s past deadline")
        if mode == "corrupt":
            raise CorruptionDetected(site)
        raise InjectedFault(site)

    def stats(self) -> Dict:
        return {"fired_total": self.fired_total,
                "fired": {s: n for s, n in self.fired.items() if n},
                "calls": {s: n for s, n in self.calls.items() if n}}

    # installable as a context manager: ``with injector: ...``
    def __enter__(self) -> "FaultInjector":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall()


class JournalEntry:
    """One request's journaled state (the supervisor's recovery unit).

    ``swapped`` (ISSUE 10) records whether the request's KV currently
    lives in the HOST tier (a swap-out payload exists for its rid) —
    host-resident state survives an engine teardown, so recovery SWAPS
    such sessions back in instead of charging them the replay prefill."""
    __slots__ = ("req", "rid", "prompt", "max_new_tokens",
                 "eos_token_id", "priority", "deadline_at",
                 "submitted_at", "tokens", "admitted", "preemptions",
                 "swapped", "adapter_id", "constrained",
                 "wal_submitted", "wal_tokens", "wal_prem",
                 "wal_swapped", "wal_admitted")

    def __init__(self, req):
        self.req = req
        self.rid = req.rid
        self.prompt = req.prompt[0].copy()
        self.max_new_tokens = req.max_new_tokens
        self.eos_token_id = req.eos_token_id
        self.priority = int(req.priority)
        self.deadline_at = req.deadline_at
        self.submitted_at = req.submitted_at
        self.tokens: List[int] = list(req.tokens)
        self.admitted = False
        self.preemptions = int(req.preemptions)
        self.swapped = False
        # the LoRA variant serving this request (ISSUE 14): journaled
        # so recovery/restore re-admissions re-pin the same adapter
        # (the handle carries it in-process; the drain record needs it
        # explicitly). Grammar-constraint STATE rides the live handle
        # only — a drain checkpoint does not serialize host DFA
        # objects, so constrained requests must finish before a drain
        # (drain() refuses while any are live; the flag is how it
        # knows).
        self.adapter_id = int(getattr(req, "adapter_id", 0))
        self.constrained = getattr(req, "constraint", None) is not None
        # durable-WAL cursors (ISSUE 15): what of this entry already
        # reached the on-disk log — sync() appends only the deltas, and
        # a failed append just leaves the cursor behind for the next
        # successful sync to heal
        self.wal_submitted = False
        self.wal_tokens = 0
        self.wal_prem = self.preemptions
        self.wal_swapped = False
        self.wal_admitted = False

    def as_record(self, now: Optional[float] = None,
                  grammars: Optional[Dict] = None) -> Dict:
        """JSON-able checkpoint record (drain/restore). Deadlines are
        serialized as REMAINING seconds against ``now`` (the draining
        supervisor's clock), never as absolute monotonic stamps — a
        monotonic value from the draining host is meaningless on the
        restoring one (different boot epoch), and would either freeze
        the SLO for days or expire still-valid requests instantly.
        Restore re-anchors against its own clock."""
        remaining = None
        if self.deadline_at is not None and now is not None:
            remaining = self.deadline_at - now
        constraint = None
        cs = getattr(self.req, "constraint", None) \
            if self.req is not None else None
        if cs is not None:
            # grammar state serializes (ISSUE 15 satellite): dense DFA
            # table + state id + violation counters — a mid-grammar
            # session survives drain/restore and cold restarts, so the
            # old drain() refusal is gone. ``grammars`` dedupes the
            # table across sessions sharing one grammar (MBs at real
            # vocab sizes — it must never re-encode per record)
            constraint = cs.to_record(grammars)
        return {"rid": self.rid, "prompt": self.prompt.tolist(),
                "max_new_tokens": self.max_new_tokens,
                "eos_token_id": self.eos_token_id,
                "priority": self.priority,
                "deadline_remaining_s": remaining,
                "tokens": list(self.tokens),
                "admitted": self.admitted,
                "preemptions": self.preemptions,
                "swapped": self.swapped,
                "adapter_id": self.adapter_id,
                "constraint": constraint}


class RequestJournal:
    """Host-side write-ahead journal of every live request.

    Admission params are recorded at SUBMIT time — before any device
    work — and committed tokens are copied in at each successful step
    (:meth:`sync`). The journal, not the engine, is the source of truth
    at recovery: a poisoned engine is discarded wholesale and every
    live request is reset to its journaled state, which is exactly the
    host state as of the last committed step (a failed step committed
    nothing — device results only reach ``req.tokens`` after the
    transfer that would have raised).

    ``wal`` (ISSUE 15) attaches a
    :class:`~paddle_tpu.serving.wal.WriteAheadLog`: admission params
    append at submit time (write-ahead — on disk before anything can
    execute), per-step committed-token deltas / preempt-swap ownership
    transitions / constraint-state deltas append at each :meth:`sync`,
    and finish / handoff-forget tombstones retire sessions from the
    log. The in-memory journal stays the in-process recovery source;
    the WAL is what a COLD restart replays
    (:meth:`EngineSupervisor.recover_from_disk`)."""

    def __init__(self, wal=None):
        self._entries: Dict[int, JournalEntry] = {}
        self.finished_total = 0
        self.wal = wal
        # finish tombstones awaiting the next due delta pass (the
        # group-commit cadence batches step deltas; a finished entry
        # leaves _entries immediately, so its tombstone must queue)
        self._pending_fin: List[tuple] = []
        # grammar tables already durably appended (hash set): many
        # sessions share one grammar, and the dense table is MBs at
        # serving vocab sizes — it goes to disk ONCE per hash, and
        # per-session records carry only the hash. Cleared at every
        # checkpoint (which carries its own grammar dict), so a
        # post-checkpoint submit re-appends tables the pruning may
        # have compacted away.
        self._wal_grammars: set = set()

    def _wal_submit(self, e: JournalEntry,
                    now: Optional[float] = None) -> None:
        grammars: Dict[str, Dict] = {}
        rec = e.as_record(now, grammars=grammars)
        rec["admitted"] = e.admitted
        for h, dfa_rec in grammars.items():
            if h not in self._wal_grammars:
                self.wal.append("grammar", {"hash": h, "dfa": dfa_rec})
        # flush=True: the write-ahead ACK — an accepted submission is
        # OS-durable before the caller gets its handle back
        self.wal.append("submit", rec, flush=True)
        # mark only after BOTH appends landed: a submit that failed
        # after its grammar record leaves the hash unmarked, and the
        # retry harmlessly re-appends it (last-wins at replay)
        self._wal_grammars.update(grammars)
        e.wal_submitted = True
        e.wal_tokens = len(e.tokens)
        e.wal_prem = e.preemptions
        e.wal_swapped = e.swapped
        e.wal_admitted = e.admitted

    def record_submit(self, req, now: Optional[float] = None
                      ) -> JournalEntry:
        e = JournalEntry(req)
        if self.wal is not None:
            # WRITE-AHEAD: the admission is on disk before the entry is
            # even registered — a failed append leaves no half-accepted
            # request (the caller sees the error before any execution)
            self._wal_submit(e, now)
        self._entries[req.rid] = e
        return e

    def adopt(self, req, rec: Dict, durable: bool = False,
              now: Optional[float] = None) -> JournalEntry:
        """Re-journal a request rebuilt from a drain checkpoint or a
        cold-restart recovery. ``durable=True`` (the recovery path)
        marks the entry as already on THIS journal's disk — its WAL
        records are the very ones recovery just replayed, so only
        future deltas append. ``now`` (the adopting supervisor's
        clock) keeps a re-anchored deadline durable: without it the
        fresh submit record would serialize the deadline as null and a
        later cold restart would silently stop enforcing the SLO."""
        e = JournalEntry(req)
        e.admitted = bool(rec.get("admitted"))
        if self.wal is not None:
            if durable:
                e.wal_submitted = True
                e.wal_tokens = len(e.tokens)
                e.wal_prem = e.preemptions
                e.wal_swapped = e.swapped
                e.wal_admitted = e.admitted
            else:
                self._wal_submit(e, now)
        self._entries[req.rid] = e
        return e

    def forget(self, rid: int) -> None:
        """Drop a live entry WITHOUT counting it finished — the
        handoff path: a request exported to another replica is that
        replica's journal's to recover now, and recovering it here too
        would decode it twice. With a WAL attached the tombstone is
        durable too, so a cold restart of THIS directory can never
        resurrect the handed-off session."""
        e = self._entries.pop(rid, None)
        if e is not None and self.wal is not None and e.wal_submitted:
            try:
                self.wal.append("forget", {"rid": rid})
            except Exception:
                pass    # in-memory ownership moved; best-effort stone

    def sync(self, swapped_check=None, wal: bool = True,
             force: bool = False) -> None:
        """Copy committed host state from the live request handles;
        finished requests leave the journal (their results live on the
        caller's handle — nothing to recover). ``swapped_check(rid) ->
        bool`` — when the engine runs a host tier — marks entries
        whose KV is host-resident (they recover by swap-in, not
        replay). The in-memory pass always completes FIRST; the WAL
        delta pass (``wal=True``) runs after it on the log's
        group-commit cadence (``force`` runs it regardless — the
        drain/checkpoint path), so an append fault can never leave the
        in-process recovery source stale."""
        finished: List[tuple] = []
        for rid in list(self._entries):
            e = self._entries[rid]
            req = e.req
            if len(e.tokens) != len(req.tokens):
                e.tokens = list(req.tokens)
            e.preemptions = int(req.preemptions)
            if (req.slot is not None or req.tokens
                    or req.preemptions > 0):
                e.admitted = True
            if swapped_check is not None:
                e.swapped = bool(swapped_check(rid))
            if req.done:
                self.finished_total += 1
                finished.append((e, req.finish_reason))
                del self._entries[rid]
        if self.wal is None:
            return
        # finished entries leave _entries NOW but their durable
        # tombstones must queue UNCONDITIONALLY — including on the
        # recovery path's wal=False sync, or a finished session's
        # submit record would stand tombstone-less forever and a later
        # cold restart would resurrect completed work
        for e, reason in finished:
            if e.wal_submitted:
                self._pending_fin.append((e.rid, reason))
        if not wal or not (force or self.wal.delta_due()):
            return
        self.wal.mark_delta()
        deltas: List[Dict] = []
        synced: List[JournalEntry] = []
        for e in list(self._entries.values()) \
                + [f[0] for f in finished]:
            if not e.wal_submitted:
                # a submit-time append failed earlier: heal with the
                # full record (write-ahead degraded to one-step lag)
                self._wal_submit(e)
                continue
            delta = {}
            if len(e.tokens) > e.wal_tokens:
                delta["toks"] = [int(t) for t in
                                 e.tokens[e.wal_tokens:]]
            if e.preemptions != e.wal_prem:
                delta["preemptions"] = e.preemptions
            if e.swapped != e.wal_swapped:
                delta["swapped"] = e.swapped
            if e.admitted != e.wal_admitted:
                delta["admitted"] = e.admitted
            if not delta:
                continue
            cs = getattr(e.req, "constraint", None)
            if cs is not None:
                delta["cstate"] = cs.state_record()
            delta["rid"] = e.rid
            deltas.append(delta)
            synced.append(e)
        fins, self._pending_fin = self._pending_fin, []
        deltas += [{"rid": rid, "fin": reason} for rid, reason in fins]
        if deltas:
            # ONE batched frame per sync: the per-record framing/flush
            # cost is what the durability rider measures per step, so a
            # B-slot commit must not pay it B times (the group-commit
            # amortization argument, applied to the frame too)
            try:
                if len(deltas) == 1 and "fin" not in deltas[0]:
                    self.wal.append("step", deltas[0])
                else:
                    self.wal.append("steps", {"entries": deltas})
            except BaseException:
                # the append committed nothing (frame-boundary
                # rollback): live deltas re-derive from the cursors on
                # the next sync, but the tombstones would be GONE —
                # re-queue them before surfacing the fault
                self._pending_fin = fins + self._pending_fin
                raise
            for e in synced:
                e.wal_tokens = len(e.tokens)
                e.wal_prem = e.preemptions
                e.wal_swapped = e.swapped
                e.wal_admitted = e.admitted

    def live_entries(self) -> List[JournalEntry]:
        return [self._entries[r] for r in sorted(self._entries)]

    @property
    def size(self) -> int:
        return len(self._entries)

    @property
    def token_count(self) -> int:
        return sum(e.prompt.size + len(e.tokens)
                   for e in self._entries.values())


def payload_checksums(arrays: Dict[str, np.ndarray]) -> Dict[str, int]:
    """Per-array CRC32s of a byte payload (ISSUE 13): computed at
    export/put time by every path that materializes KV bytes (handoff
    export, host-tier swap/demote, standing-store writes) and verified
    by :func:`verify_checksums` before any install — a corrupt or torn
    payload becomes a :class:`CorruptionDetected` at the door, never a
    silently-wrong KV page."""
    return {n: zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF
            for n, a in arrays.items()}


def verify_checksums(arrays: Dict[str, np.ndarray],
                     checksums: Optional[Dict[str, int]],
                     site: str) -> None:
    """Verify ``arrays`` against :func:`payload_checksums` output;
    raises :class:`CorruptionDetected` (tagged ``site``) on any
    mismatch or missing array entry. A payload with no checksum dict
    (pre-ISSUE-13 producer) passes — verification is the consumer's
    defense, not a format break."""
    if not checksums:
        return
    lost = set(checksums) - set(arrays)
    if lost:
        # the inverse hole: a checksummed array VANISHED from the
        # payload (partial rewrite / truncation that dropped a whole
        # member) — that is corruption, not a geometry mismatch
        raise CorruptionDetected(
            site, f"payload lost checksummed array(s) {sorted(lost)} "
            f"— truncated payload")
    for name, a in arrays.items():
        want = checksums.get(name)
        if want is None:
            raise CorruptionDetected(
                site, f"payload array {name!r} has no checksum — "
                f"truncated or foreign payload")
        got = zlib.crc32(np.ascontiguousarray(a).tobytes()) & 0xFFFFFFFF
        if got != int(want):
            raise CorruptionDetected(
                site, f"payload array {name!r} checksum mismatch "
                f"(expected {int(want)}, got {got})")


def _np_dtype(name: str) -> np.dtype:
    """Resolve a checkpointed dtype name, including the ml_dtypes
    extension types (bfloat16 & friends) numpy can't look up by
    string."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def load_drain_checkpoint(path: str) -> Dict:
    """Decode a :meth:`EngineSupervisor.drain` ``.npz`` back into host
    data: ``meta`` (sessions, geometry, next_rid), ``key_data`` (PRNG
    snapshot, empty when none) and — when a prefix trie was
    checkpointed — ``prefix`` in the exact dict shape
    :meth:`~paddle_tpu.serving.PagedKVCache.restore_prefix` consumes.
    Shared by :meth:`EngineSupervisor.restore` (whole-supervisor
    restore) and the cluster's rolling upgrade
    (:meth:`~paddle_tpu.serving.cluster.ServingCluster.retire_replica`
    restores ONLY the trie into the replacement replica — the sessions
    were requeued live onto other replicas)."""
    with np.load(path) as data:
        meta = json.loads(bytes(data["meta"]).decode())
        key_data = np.asarray(data["key_data"])
        prefix = None
        if meta["prefix"] is not None:
            pf = meta["prefix"]
            arrays = {
                n: np.frombuffer(
                    bytes(data[f"prefix_{n}"]),
                    _np_dtype(pf["dtypes"][n])).reshape(pf["shapes"][n])
                for n in pf["shapes"]}
            prefix = {"page_ids": pf["page_ids"],
                      "records": pf["records"], "arrays": arrays}
    return {"meta": meta, "key_data": key_data, "prefix": prefix}


def _session_from_record(sup: "EngineSupervisor", rec: Dict,
                         grammars: Optional[Dict] = None):
    """Rebuild one live request handle from a checkpoint/WAL session
    record (shared by :meth:`EngineSupervisor.restore` and
    :meth:`EngineSupervisor.recover_from_disk`): admission params,
    committed tokens, re-anchored deadline, adapter pin, swapped flag
    and — when the session was grammar-constrained — an equivalent
    :class:`~paddle_tpu.serving.constraints.ConstraintState` attached
    through the engine's validated surface."""
    from ..inference.predictor import GenerationRequest
    req = GenerationRequest(
        rec["rid"], np.asarray(rec["prompt"], np.int32),
        rec["max_new_tokens"], rec.get("eos_token_id"))
    req.priority = rec.get("priority", 1)
    req.adapter_id = int(rec.get("adapter_id", 0))
    if rec.get("deadline_remaining_s") is not None:
        # re-anchor the SLO on THIS process's clock (records store
        # remaining seconds, never monotonic stamps from the dead host)
        req.deadline_at = sup.clock() + rec["deadline_remaining_s"]
    req.tokens = list(rec.get("tokens") or ())
    # a swapped-out session's host payload may have died with the
    # process (host RAM) or survived (shared/standing store): the
    # admit-time swap-in probes and falls back to the gated replay
    # resume either way, so the flag is safe to carry verbatim
    req.swapped = bool(rec.get("swapped"))
    if rec.get("admitted"):
        req.preemptions = int(rec.get("preemptions", 0)) + 1
        req.finish_reason = FinishReason.PREEMPTED.value
    if rec.get("constraint") is not None:
        from .constraints import ConstraintState
        sup.engine.attach_constraint(
            req, ConstraintState.from_record(rec["constraint"],
                                             grammars=grammars))
    return req


class EngineSupervisor:
    """Crash-recovering wrapper around engine + scheduler.

    ``engine_factory() -> ContinuousBatchingEngine`` must build a FRESH
    engine with an identical configuration each call — the supervisor
    invokes it at construction and after every teardown ("rebuild pools
    from scratch"). Compiled step programs are carried across rebuilds
    (they are pure functions of their array arguments; only the pools
    and host bookkeeping are poisoned), so a recovery costs journal
    replay, not recompilation.

    Lifecycle knobs:

    - ``watchdog_s``: run each step on a watchdog thread and declare
      :class:`StepStalled` past the deadline (None = no watchdog; a
      genuinely hung step then blocks forever, as before).
    - ``backoff_s`` / ``backoff_max_s``: exponential backoff slept
      between consecutive failures (injectable ``sleep`` for tests).
    - ``circuit_threshold``: consecutive failed step attempts (no
      successful step in between) before the breaker opens — the
      supervisor marks every live request ``engine_dead``, reports
      ``health == "dead"`` and raises :class:`EngineDead`.
    - ``recover_after``: consecutive successful steps per rung of
      degraded-ladder descent.

    Degraded ladder (:data:`DEGRADED_MODES`): every recovery escalates
    one rung — 1: speculative decoding off (the most failure-adjacent
    optional program); 2: prefill chunk shrunk to one page (smallest
    step granularity, fastest fault isolation); 3: LOW-priority
    admissions shed at submit with the structured ``rejected_overload``
    finish reason. The current rung is published to the metrics
    registry (``serving_degraded_mode``) — the signal ROADMAP item 2's
    router will steer replicas by.
    """

    def __init__(self, engine_factory: Callable, *,
                 token_budget: Optional[int] = None,
                 watchdog_s: Optional[float] = None,
                 backoff_s: float = 0.05, backoff_max_s: float = 2.0,
                 circuit_threshold: int = 5, recover_after: int = 32,
                 reuse_compiled: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 scheduler_kw: Optional[Dict] = None,
                 wal_dir: Optional[str] = None,
                 wal_fsync: str = "group",
                 wal_kw: Optional[Dict] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_prefix: bool = False,
                 flight_ticks: int = 256):
        self._factory = engine_factory
        self.token_budget = token_budget
        self.watchdog_s = watchdog_s
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.circuit_threshold = int(circuit_threshold)
        self.recover_after = int(recover_after)
        self.reuse_compiled = reuse_compiled
        self.clock = clock
        self._sleep = sleep
        self._sched_kw = dict(scheduler_kw or {})
        # durable journal plane (ISSUE 15): wal_dir attaches an on-disk
        # write-ahead log under the journal — admissions/token commits/
        # ownership transitions become crash-durable, periodic
        # incremental checkpoints compact the log without stopping
        # admissions, and EngineSupervisor.recover_from_disk() rebuilds
        # a cold-started process from the directory alone
        self.wal = None
        self.checkpoint_every = checkpoint_every
        self.checkpoint_prefix = bool(checkpoint_prefix)
        if wal_dir is not None:
            from .wal import WriteAheadLog
            self.wal = WriteAheadLog(wal_dir, fsync=wal_fsync,
                                     **(wal_kw or {}))
        self.journal = RequestJournal(wal=self.wal)
        self.degraded_level = 0
        self.recoveries = 0
        self.injected_faults = 0
        self.real_faults = 0
        self.shed_total = 0
        self.steps_total = 0
        self._consec_failures = 0
        self._successes_since_change = 0
        self._next_rid = 0
        self._key_data: Optional[np.ndarray] = None
        self._spec_shelf = None
        self._chunk_shelf = None
        self._chunk_shrunk = False
        self._dead = False
        self._draining = False
        self.engine = None
        self.scheduler = None
        self.restored: Dict[int, object] = {}
        # crash flight recorder (ISSUE 16): a fixed ring of the last N
        # scheduler ticks, dumped as a CRC-framed black box on
        # EngineDead / any exception escaping step() / on demand.
        # flight_ticks=0 disables the recorder entirely.
        self._replica_id = -1
        self.flight = None
        if flight_ticks:
            from ..observability.flight import FlightRecorder
            self.flight = FlightRecorder(max_ticks=flight_ticks,
                                         meta={"replica": -1})
        self.last_flight_dump: Optional[str] = None
        self._build()
        self._snapshot_key()
        if self.wal is not None:
            # geometry record: cold recovery validates the replacement
            # engine against it (the restore() contract, made durable)
            cache = self.engine.cache
            self.wal.append("meta", {
                "page_size": cache.page_size, "max_len": cache.max_len,
                "max_batch": cache.max_batch,
                "kv_dtype": (str(np.dtype(cache.kv_dtype))
                             if cache.kv_dtype is not None else None),
                "constraints": bool(getattr(self.engine, "constraints",
                                            False)),
                "draft": _draft_identity(self.engine),
                "next_rid": self._next_rid})
            self.wal.commit(force=True)

    # ---- health ----
    @property
    def health(self) -> str:
        if self._dead:
            return "dead"
        return "healthy" if self.degraded_level == 0 else "degraded"

    @property
    def degraded_mode(self) -> str:
        return DEGRADED_MODES[self.degraded_level]

    @property
    def replica_id(self) -> int:
        """Cluster replica index carried by trace spans and flight
        dumps; -1 for a standalone supervisor. The setter propagates to
        the engine (and :meth:`_build` re-stamps across rebuilds), so
        cross-replica handoffs stitch into one trace."""
        return self._replica_id

    @replica_id.setter
    def replica_id(self, value: int) -> None:
        self._replica_id = int(value)
        if self.engine is not None:
            self.engine.replica_id = self._replica_id
        if self.flight is not None:
            self.flight.meta["replica"] = self._replica_id

    def _check_alive(self):
        if self._dead:
            raise EngineDead(
                "circuit breaker open after "
                f"{self.circuit_threshold} consecutive step failures")
        if self._draining:
            raise RuntimeError(
                "EngineSupervisor was drained; restore the checkpoint "
                "into a fresh supervisor (EngineSupervisor.restore)")

    # ---- build / teardown ----
    def _build(self):
        """(Re)build the engine + scheduler pair from scratch. Pools,
        allocator, trie, slots all start empty; compiled step programs
        carry over from the previous engine when configurations match
        (pure functions of their array arguments — only state was
        poisoned, not code)."""
        from .scheduler import ServingScheduler
        old = self.engine
        eng = self._factory()
        if not eng.idle:
            raise ValueError(
                "engine_factory must return a FRESH engine (no queued "
                "or running requests)")
        eng._next_rid = max(eng._next_rid, self._next_rid)
        if (old is not None and self.reuse_compiled
                and old.temperature == eng.temperature
                and old.use_kernel == eng.use_kernel
                and old._tp == eng._tp):
            eng._decode_fn = old._decode_fn
            eng._chunk_fns = old._chunk_fns
            eng._spec_fns = old._spec_fns
            eng.cache._cow_fn = old.cache._cow_fn
            eng.cache._scatter_fn = old.cache._scatter_fn
        if (old is not None
                and hasattr(eng.cache, "adopt_host_tier")
                and hasattr(old.cache, "adopt_host_tier")):
            # hierarchical KV (ISSUE 10): the host tier is HOST state
            # committed only after successful device→host gathers — it
            # survives the poisoned pool, so swapped-out sessions (and
            # the standing prefix store) carry into the rebuilt engine
            # and recovery SWAPS them in instead of replaying
            eng.cache.adopt_host_tier(old.cache)
        pool = getattr(eng, "adapters", None)
        if (pool is not None and old is not None
                and getattr(old, "adapters", None) is pool):
            # the adapter pool rode across the rebuild (the factory
            # closes over one pool, the usual shape): stale pins from
            # the poisoned engine's rows must not leak slots — recovery
            # re-admits every journaled session through acquire(),
            # which re-pins exactly the live set
            pool.reset_pins()
        if self._key_data is not None:
            import jax
            import jax.numpy as jnp
            eng._key = jax.random.wrap_key_data(
                jnp.asarray(self._key_data))
        self.engine = eng
        # re-stamp the replica identity across rebuilds (ISSUE 16) —
        # spans from the recovered engine must land in the same lane
        eng.replica_id = getattr(self, "_replica_id", -1)
        self.scheduler = ServingScheduler(
            eng, token_budget=self.token_budget, clock=self.clock,
            **self._sched_kw)
        self._apply_degraded()

    def _fence(self, old):
        """Best-effort fence on the poisoned engine: an abandoned
        (stalled) step thread that wakes later finds empty slot/pending
        tables and commits nothing. Injected stalls never commit anyway
        (they raise on wake); this narrows the window for real ones."""
        if old is None:
            return
        old._slots = [None] * old.max_batch
        old._pending = {}
        old._queue = []
        # drop dispatched-but-uncommitted work with the poisoned engine
        # (ISSUE 12): the journal holds the last COMMITTED state, so
        # the lost in-flight result is recomputed by the replay —
        # token-identically (the fault-between-dispatch-and-commit gate)
        old._inflight = None
        old._inflight_chunks = []

    def _snapshot_key(self):
        import jax
        self._key_data = np.asarray(jax.random.key_data(self.engine._key))

    # ---- degraded ladder ----
    def _apply_degraded(self):
        """Impose the current rung on the live engine (called on every
        rebuild and escalation; shelves keep what descent restores)."""
        eng = self.engine
        if self.degraded_level >= 1:
            if eng.spec is not None:
                self._spec_shelf = eng.spec
                eng.spec = None
        elif eng.spec is None and self._spec_shelf is not None:
            eng.spec = self._spec_shelf
            self._spec_shelf = None
        if self.degraded_level >= 2:
            if not self._chunk_shrunk:
                self._chunk_shelf = eng.prefill_chunk
                self._chunk_shrunk = True
            eng.prefill_chunk = eng.cache.page_size
        elif self._chunk_shrunk:
            eng.prefill_chunk = self._chunk_shelf
            self._chunk_shrunk = False
        if self.scheduler is not None:
            # mirror the rung onto the scheduler so load_stats() is a
            # complete health snapshot (the router's signal) even with
            # the metrics registry disabled
            self.scheduler.degraded_level = self.degraded_level
        _obs.serving_degraded(self.degraded_level)

    def _escalate(self):
        if self.degraded_level < len(DEGRADED_MODES) - 1:
            self.degraded_level += 1
        self._successes_since_change = 0
        self._apply_degraded()

    def _deescalate_maybe(self):
        if self.degraded_level == 0:
            return
        self._successes_since_change += 1
        if self._successes_since_change >= self.recover_after:
            self.degraded_level -= 1
            self._successes_since_change = 0
            self._apply_degraded()

    # ---- intake ----
    def submit(self, prompt, max_new_tokens: int = 16, *,
               priority=Priority.NORMAL,
               deadline_s: Optional[float] = None, eos_token_id=None,
               adapter_id: int = 0, constraint=None):
        """Journaled submit (write-ahead: the admission params are on
        the journal before anything can execute). At degraded level 3
        (``shed_low``) LOW-priority requests are rejected immediately
        with the structured ``rejected_overload`` finish reason instead
        of queueing into an engine that keeps failing."""
        self._check_alive()
        req = self.engine.create_request(
            prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, adapter_id=adapter_id,
            constraint=constraint)
        req.priority = int(priority)
        self._next_rid = self.engine._next_rid
        return self.submit_request(req, deadline_s=deadline_s)

    def submit_request(self, req, *, deadline_s: Optional[float] = None):
        """Journaled intake of an EXISTING request handle — the
        cluster router's dispatch (and re-dispatch) path (ISSUE 9).
        The shed-LOW ladder applies only to FRESH requests: a handle
        that already committed tokens (or was preempted) is in-flight
        work being rehomed, and shedding it would lose it."""
        self._check_alive()
        fresh = not req.tokens and req.preemptions == 0
        if (fresh and self.degraded_level >= 3
                and int(req.priority) >= int(Priority.LOW)):
            req.done = True
            req.finish_reason = FinishReason.REJECTED_OVERLOAD.value
            self.shed_total += 1
            _obs.serving_cancelled(1, req.finish_reason)
            return req
        self.engine._next_rid = max(self.engine._next_rid, req.rid + 1)
        self._next_rid = max(self._next_rid, self.engine._next_rid)
        if deadline_s is not None:
            req.deadline_at = self.clock() + float(deadline_s)
        # write-ahead BEFORE the queue: a failed durable append rejects
        # the submission here, with the caller watching — never a
        # request the engine acknowledged but disk never heard of
        try:
            self.journal.record_submit(req, now=self.clock())
        except BaseException as exc:
            # a submit-path death never reaches step()'s dump hook —
            # leave the black box on this exit too (ISSUE 16)
            self._flight_dump_safe(type(exc).__name__, err=str(exc))
            raise
        self.scheduler.requeue(req)
        return req

    def adopt_running(self, req):
        """Journal a request installed DIRECTLY into a running slot
        (the decode side of a prefill→decode handoff —
        :meth:`~paddle_tpu.inference.ContinuousBatchingEngine.import_prefilled`
        bypasses the admission queue): from here this supervisor owns
        its recovery (a crash replays ``prompt + tokens[:-1]`` through
        THIS engine's continuation prefill, token-identically)."""
        self._check_alive()
        self.engine._next_rid = max(self.engine._next_rid, req.rid + 1)
        self._next_rid = max(self._next_rid, self.engine._next_rid)
        e = self.journal.record_submit(req, now=self.clock())
        e.admitted = True
        if self.journal.wal is not None and e.wal_submitted \
                and not e.wal_admitted:
            # the adopt side of a handoff owns recovery from here: make
            # the admitted flag durable with the submit record's lsn
            # neighborhood, not a whole step later
            self.journal.wal.append("step", {"rid": e.rid,
                                             "admitted": True})
            e.wal_admitted = True
        return req

    # ---- stepping ----
    def _guarded(self, fn):
        if self.watchdog_s is None:
            return fn()
        box: Dict = {}

        def run():
            try:
                box["r"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["e"] = e

        t = threading.Thread(target=run, daemon=True,
                             name="supervised-engine-step")
        t.start()
        t.join(self.watchdog_s)
        if t.is_alive():
            raise StepStalled(self.watchdog_s)
        if "e" in box:
            raise box["e"]
        return box.get("r")

    def step(self) -> bool:
        """One supervised scheduler step. A failure triggers teardown +
        journal recovery and the step is retried on the rebuilt engine;
        the circuit breaker bounds consecutive failures. Returns False
        when no work remains. The post-step bookkeeping
        (:meth:`_on_success`: journal sync, WAL append/group-commit,
        incremental checkpoint) is inside the failure domain too — a
        durable-log fault recovers exactly like a device fault, and
        the retried step re-runs against the requeued sessions."""
        self._check_alive()
        try:
            return self._step_supervised()
        except BaseException as exc:
            # black box on the way out (ISSUE 16): EngineDead (circuit
            # open) and anything a failure handler re-raised leave a
            # flight dump next to the journal before propagating —
            # even when _on_failure itself was replaced (the chaos
            # harness's process-kill surrogate raises from inside it)
            self._flight_dump_safe(type(exc).__name__, err=str(exc))
            raise

    def _step_supervised(self) -> bool:
        while True:
            try:
                alive = self._guarded(self.scheduler.step)
                self._on_success()
                self._record_flight_tick()
                if not alive and self.wal is not None:
                    # going idle: force the buffered delta pass + fsync
                    # so a QUIESCENT supervisor is always durably
                    # consistent — the group-commit loss window only
                    # ever spans work actually in flight (a crash
                    # mid-window replays it token-identically; it must
                    # not resurrect work that visibly finished)
                    self._sync_journal(force=True)
                    self.wal.commit(force=True)
            except EngineDead:
                raise
            except Exception as e:  # noqa: BLE001 — classify + recover
                self._on_failure(e)
                continue
            return alive

    def run(self) -> None:
        """Drive steps until every request finished (raises
        :class:`EngineDead` if the circuit opens first)."""
        while self.step():
            pass

    def _sync_journal(self, wal: bool = True, force: bool = False):
        self.journal.sync(swapped_check=getattr(
            self.engine.cache, "has_swapped", None), wal=wal,
            force=force)

    def _on_success(self):
        self.steps_total += 1
        self._consec_failures = 0
        self._sync_journal()
        self._snapshot_key()
        if self.wal is not None:
            if (self.engine.temperature != 0.0
                    and self._key_data is not None):
                # sampled decode: the PRNG snapshot is recovery state
                # (greedy replay never consults it — skip the bytes)
                import base64
                self.wal.append("key", {
                    "data": base64.b64encode(
                        self._key_data.tobytes()).decode(),
                    "dtype": str(self._key_data.dtype),
                    "shape": list(self._key_data.shape)})
            self.wal.commit()       # the group-commit boundary
            if (self.checkpoint_every
                    and self.steps_total % self.checkpoint_every == 0):
                self.checkpoint_now()
        self._deescalate_maybe()
        _obs.serving_journal(self.journal.size, self.journal.token_count)

    # ---- flight recorder (ISSUE 16) ----
    def _record_flight_tick(self, fault: Optional[str] = None) -> None:
        """Fold one scheduler tick into the flight ring: plan summary,
        budget use, degraded rung, failure streak, WAL lsn. One small
        dict append — noise next to the WAL append the tick already
        paid; no-op when the recorder is disabled."""
        if self.flight is None:
            return
        sched = self.scheduler
        plan = sched.last_plan if sched is not None else None
        self.flight.record_tick(
            step=self.steps_total,
            committed=(sched.last_committed if sched is not None else 0),
            planned_tokens=(plan.scheduled_tokens if plan is not None
                            else 0),
            reserved_tokens=(plan.reserved_tokens if plan is not None
                             else 0),
            budget=(plan.budget if plan is not None else None),
            decode_slots=(len(plan.decode_slots) if plan is not None
                          else 0),
            prefills=(len(plan.prefills) if plan is not None else 0),
            queued=(sum(len(q) for q in sched._queues.values())
                    if sched is not None else 0),
            degraded=self.degraded_level,
            failures=self._consec_failures,
            host_frac=(sched.last_host_frac if sched is not None
                       else None),
            wal_lsn=(self.wal.lsn if self.wal is not None else None),
            fault=fault)
        _obs.serving_flight_tick()

    def dump_flight(self, reason: str = "manual",
                    out_dir: Optional[str] = None,
                    err: Optional[str] = None) -> Optional[str]:
        """Write the flight-recorder black box (on demand, and the
        crash paths' exit hatch): the tick ring + request-trace tails
        as a CRC-framed ``flight-<ts>.json`` in ``out_dir`` (default:
        the WAL/journal directory, else the system temp dir). Returns
        the path; None when the recorder is disabled."""
        if self.flight is None:
            return None
        if out_dir is None:
            out_dir = (self.wal.path if self.wal is not None
                       else tempfile.gettempdir())
        extra = {"health": self.health,
                 "degraded_level": self.degraded_level,
                 "consec_failures": self._consec_failures,
                 "recoveries": self.recoveries,
                 "steps_total": self.steps_total}
        if err:
            extra["error"] = err
        path = self.flight.dump(out_dir, reason, extra=extra)
        self.last_flight_dump = path
        _obs.serving_flight_dump(reason, os.path.getsize(path))
        return path

    def _flight_dump_safe(self, reason: str, err: str = "") -> None:
        """Best-effort dump on the crash path — a second failure here
        must never mask the one propagating."""
        try:
            self.dump_flight(reason, err=err)
        except Exception:
            pass

    def checkpoint_now(self) -> Optional[str]:
        """One INCREMENTAL checkpoint (ISSUE 15): snapshot the live
        journal + PRNG key (and, with ``checkpoint_prefix``, the
        prefix-trie pages — the drain machinery) into an atomic
        ``ckpt-<lsn>.npz`` next to the log, then prune the segments it
        covers. Admissions never stop — this is a host-side call
        between steps; cold recovery is checkpoint + log-suffix
        replay."""
        if self.wal is None:
            return None
        now = self.clock()
        cache = self.engine.cache
        grammars: Dict[str, Dict] = {}
        meta = {
            "sessions": [e.as_record(now, grammars=grammars)
                         for e in self.journal.live_entries()],
            "grammars": grammars,
            "next_rid": self._next_rid,
            "page_size": cache.page_size,
            "max_len": cache.max_len,
            "max_batch": cache.max_batch,
            "kv_dtype": (str(np.dtype(cache.kv_dtype))
                         if cache.kv_dtype is not None else None),
            "constraints": bool(getattr(self.engine, "constraints",
                                        False)),
            "draft": _draft_identity(self.engine),
            "prefix": None,
        }
        arrays: Dict[str, np.ndarray] = {
            "key_data": self._key_data if self._key_data is not None
            else np.zeros((0,), np.uint32)}
        if self.checkpoint_prefix:
            ckpt = cache.checkpoint_prefix()
            if ckpt is not None:
                meta["prefix"] = {
                    "page_ids": ckpt["page_ids"],
                    "records": ckpt["records"],
                    "shapes": {n: list(a.shape)
                               for n, a in ckpt["arrays"].items()},
                    "dtypes": {n: str(a.dtype)
                               for n, a in ckpt["arrays"].items()},
                }
                for n, a in ckpt["arrays"].items():
                    arrays[f"prefix_{n}"] = np.frombuffer(
                        np.ascontiguousarray(a).tobytes(), np.uint8)
        path = self.wal.checkpoint(meta, arrays)
        # the checkpoint carries its own grammar dict and pruning may
        # compact away earlier grammar records: future submits must
        # re-append their tables, so the dedupe set resets here
        self.journal._wal_grammars.clear()
        return path

    def _on_failure(self, err: Exception):
        stalled = isinstance(err, StepStalled)
        injected = isinstance(err, InjectedFault)
        site = getattr(err, "site", None) or "step"
        kind = getattr(err, "mode", None) or type(err).__name__
        inj = _ACTIVE
        if stalled and not injected:
            # the watchdog only ever sees a StepStalled — ask the
            # installed injector whether the stall was its own, so
            # chaos runs never inflate the REAL-failure counter (and a
            # genuine stall during a chaos run is at worst attributed
            # to the one pending injection, never silently dropped)
            if inj is not None and inj.pending_stalls:
                site = inj.pending_stalls.pop(0)
                injected = True
        elif injected and kind == "stall":
            # the stall woke BEFORE the watchdog (stall_s < watchdog_s)
            # and raised itself: retire its pending entry, or a later
            # REAL watchdog stall would be misattributed as injected
            if inj is not None and site in inj.pending_stalls:
                inj.pending_stalls.remove(site)
        if injected:
            self.injected_faults += 1
            # the injector already counted itself at fire time
        else:
            self.real_faults += 1
            _obs.serving_fault(site, kind, injected=False)
        self._consec_failures += 1
        # a faulted tick never reached the success-path recorder —
        # fold it in here so the black box shows the firing itself
        self._record_flight_tick(fault=f"{site}:{kind}")
        if self._consec_failures >= self.circuit_threshold:
            self._die(err)
        self._sleep(min(self.backoff_max_s,
                        self.backoff_s
                        * (2 ** (self._consec_failures - 1))))
        self._recover(sync=not stalled)

    def _die(self, err: Exception):
        """Open the circuit: mark every live request with the
        structured ``engine_dead`` reason (nothing is silently lost —
        the journal is retained for post-mortem/drain tooling) and stop
        retrying."""
        self._dead = True
        for e in self.journal.live_entries():
            req = e.req
            if req is not None and not req.done:
                req.done = True
                req.finish_reason = "engine_dead"
        if self.scheduler is not None:
            self.scheduler.degraded_level = len(DEGRADED_MODES)
        _obs.serving_degraded(len(DEGRADED_MODES))  # off-ladder: dead
        raise EngineDead(
            f"circuit breaker open after {self._consec_failures} "
            f"consecutive step failures; last: "
            f"{type(err).__name__}: {err}") from err

    def _recover(self, sync: bool = True):
        """Teardown + rebuild + journal restore. ``sync=False`` for
        stalls: the abandoned thread may still be running, so the
        journal keeps its last-committed state instead of reading the
        handles mid-race."""
        t0 = _obs.generate_begin()
        if sync:
            # in-memory only: the WAL delta pass is deferred to the
            # next successful step's sync — a recovery triggered BY a
            # WAL fault must not re-enter the faulting append mid-
            # recovery (the cursors heal once appends succeed again)
            self._sync_journal(wal=False)
        live = self.journal.live_entries()
        # host-resident sessions (ISSUE 10) swap back in: their resume
        # is one page scatter, not a replay — the recovery bill counts
        # only the sessions that actually re-forward tokens
        replay = sum(e.prompt.size + max(0, len(e.tokens) - 1)
                     for e in live if e.admitted and not e.swapped)
        self._fence(self.engine)
        self._build()
        for e in live:
            req = e.req
            req.slot = None
            req.done = False
            req.tokens = list(e.tokens)
            if e.admitted:
                # a crashed-out session is an eviction the request never
                # asked for: resume semantics (transient reason, replay
                # accounting, deadline exemption) apply verbatim
                req.preemptions = e.preemptions + 1
                req.finish_reason = FinishReason.PREEMPTED.value
            else:
                req.finish_reason = None
            self.scheduler.requeue(req)
        self.recoveries += 1
        self._escalate()
        _obs.serving_fault_recovery(t0, len(live), replay)

    # ---- drain / restore ----
    def drain(self, path: str) -> Dict:
        """Stop admissions and checkpoint to ``path`` (one ``.npz``):
        every live session's journal record, the prefix-cache trie
        (structure + page KV bytes), the PRNG key snapshot and the
        engine geometry for restore-time validation. The supervisor is
        frozen afterwards (submit/step raise) — restore the file into a
        fresh process via :meth:`restore`. Returns a summary dict.

        Live grammar-constrained sessions checkpoint too (ISSUE 15
        satellite — the old refusal is gone): each session record
        carries the serialized DFA table + live state id + violation
        counters, and :meth:`restore` re-attaches an equivalent
        :class:`~paddle_tpu.serving.constraints.ConstraintState`, so a
        mid-grammar session resumes always-valid and token-identical
        (gated in tests/test_wal.py)."""
        self._check_alive()
        t0 = _obs.generate_begin()
        # the overlapped runtime (ISSUE 12) may hold a dispatched-but-
        # uncommitted step: commit it so sessions checkpoint with every
        # token the device already produced (no-op when synchronous)
        self.engine.commit_inflight()
        self._sync_journal(force=True)
        self._snapshot_key()
        now = self.clock()
        cache = self.engine.cache
        ckpt = cache.checkpoint_prefix()
        grammars: Dict[str, Dict] = {}
        meta = {
            "sessions": [e.as_record(now, grammars=grammars)
                         for e in self.journal.live_entries()],
            "grammars": grammars,
            "next_rid": self._next_rid,
            "page_size": cache.page_size,
            "max_len": cache.max_len,
            "max_batch": cache.max_batch,
            "kv_dtype": (str(np.dtype(cache.kv_dtype))
                         if cache.kv_dtype is not None else None),
            "prefix": None,
        }
        arrays: Dict[str, np.ndarray] = {
            "key_data": self._key_data if self._key_data is not None
            else np.zeros((0,), np.uint32)}
        if ckpt is not None:
            meta["prefix"] = {
                "page_ids": ckpt["page_ids"],
                "records": ckpt["records"],
                "shapes": {n: list(a.shape)
                           for n, a in ckpt["arrays"].items()},
                "dtypes": {n: str(a.dtype)
                           for n, a in ckpt["arrays"].items()},
            }
            for n, a in ckpt["arrays"].items():
                # raw-byte views round-trip extension dtypes (bf16)
                # that np.savez cannot serialize natively
                arrays[f"prefix_{n}"] = np.frombuffer(
                    np.ascontiguousarray(a).tobytes(), np.uint8)
        with open(path, "wb") as f:
            np.savez(f, meta=np.frombuffer(
                json.dumps(meta).encode(), np.uint8), **arrays)
        # freeze ONLY once the checkpoint is safely on disk: a failed
        # write (bad path, disk full) leaves the supervisor serving —
        # bricking a healthy engine with nothing saved would strand
        # every in-flight session
        self._draining = True
        if self.wal is not None:
            # the drain checkpoint owns these sessions now: tombstone
            # them in the WAL (and fsync) so a cold recovery of this
            # directory can never resurrect what restore() will also
            # revive elsewhere — exactly one recovery owner
            try:
                for e in self.journal.live_entries():
                    if e.wal_submitted:
                        self.wal.append("finish", {"rid": e.rid,
                                                   "reason": "drained"})
                self.wal.commit(force=True)
                self.wal.close()
            except Exception:
                pass        # drain file is authoritative regardless
        nbytes = os.path.getsize(path)
        n_pages = len(meta["prefix"]["page_ids"]) if meta["prefix"] \
            else 0
        _obs.serving_drain_checkpoint(t0, nbytes,
                                      len(meta["sessions"]), n_pages)
        return {"path": path, "bytes": nbytes,
                "sessions": len(meta["sessions"]),
                "trie_pages": n_pages}

    @classmethod
    def restore(cls, engine_factory: Callable, path: str,
                **kw) -> "EngineSupervisor":
        """Build a fresh supervisor and restore a :meth:`drain`
        checkpoint into it: trie pages are written back into the new
        pool FIRST (so session replays — and future admissions — hit
        the restored prefix cache), then every checkpointed session is
        requeued through the resume path. Restored request handles live
        in ``.restored`` (rid -> request)."""
        sup = cls(engine_factory, **kw)
        t0 = _obs.generate_begin()
        ckpt = load_drain_checkpoint(path)
        meta = ckpt["meta"]
        cache = sup.engine.cache
        for knob in ("page_size", "max_len", "max_batch"):
            if meta[knob] != getattr(cache, knob):
                raise ValueError(
                    f"restore: checkpoint {knob}={meta[knob]} does "
                    f"not match the fresh engine's "
                    f"{getattr(cache, knob)} — the factory must "
                    f"rebuild the drained engine's geometry")
        kv = (str(np.dtype(cache.kv_dtype))
              if cache.kv_dtype is not None else None)
        if meta["kv_dtype"] != kv:
            raise ValueError(
                f"restore: checkpoint kv_dtype={meta['kv_dtype']} "
                f"!= engine kv_dtype={kv}")
        draft = _draft_identity(sup.engine)
        if meta.get("draft") != draft:
            raise ValueError(
                f"restore: checkpoint draft identity="
                f"{meta.get('draft')} != engine {draft} — the factory "
                f"must rebuild the same draft_layers/spec_tree (the "
                f"draft pool itself rebuilds cold)")
        key_data = ckpt["key_data"]
        if key_data.size:
            import jax
            import jax.numpy as jnp
            sup._key_data = key_data
            sup.engine._key = jax.random.wrap_key_data(
                jnp.asarray(key_data))
        n_pages = 0
        if ckpt["prefix"] is not None:
            cache.restore_prefix(ckpt["prefix"])
            n_pages = len(ckpt["prefix"]["page_ids"])
        sup._next_rid = int(meta["next_rid"])
        sup.engine._next_rid = max(sup.engine._next_rid, sup._next_rid)
        sup.restored: Dict[int, object] = {}
        for rec in meta["sessions"]:
            req = _session_from_record(sup, rec,
                                       grammars=meta.get("grammars"))
            sup.journal.adopt(req, rec, now=sup.clock())
            sup.scheduler.requeue(req)
            sup.restored[req.rid] = req
        _obs.serving_drain_restore(t0, os.path.getsize(path),
                                   len(meta["sessions"]), n_pages)
        return sup

    # ---- cold-restart recovery (ISSUE 15) ----
    @classmethod
    def recover_from_disk(cls, engine_factory: Callable, wal_dir: str,
                          **kw) -> "EngineSupervisor":
        """Rebuild a supervisor from its durable journal directory
        after WHOLE-PROCESS death (``kill -9``, OOM-kill, host reboot
        — no drain, no in-memory journal): scan the WAL (torn tail
        truncated at the last valid frame, corrupt media quarantined,
        newest VALID checkpoint + log-suffix replay), build a fresh
        engine, and requeue every journaled live session through the
        ``resume_sequence`` replay path — token-identical to an
        uninterrupted run, the same gate the in-process recovery
        carries (tests/test_wal.py crash-point sweep). The recovered
        supervisor keeps appending to the SAME directory, so repeated
        crashes recover repeatedly."""
        from .wal import recover_state
        t0 = _obs.generate_begin()
        state = recover_state(wal_dir, repair=True)
        kw = dict(kw)
        wk = dict(kw.get("wal_kw") or {})
        # the scan just ran (and repaired): hand its lsn to the fresh
        # log so construction doesn't re-read every segment
        wk.setdefault("last_lsn", state["report"]["last_lsn"])
        kw["wal_kw"] = wk
        sup = cls(engine_factory, wal_dir=wal_dir, **kw)
        try:
            sup._install_recovered(state, t0)
        except Exception:
            # a REFUSED recovery (factory geometry / kv tier / draft
            # identity mismatch) must be side-effect-free on the
            # journal: construction above already appended the fresh
            # engine's meta record, so latest-wins would hand the NEXT
            # attempt the wrong factory's identity to validate against
            # — re-append the dead incarnation's geometry so a retry
            # with the correct factory still recovers
            geo = state.get("geometry")
            if geo is not None and sup.wal is not None:
                sup.wal.append("meta", dict(
                    geo, next_rid=int(state.get("next_rid", 0))))
                sup.wal.commit(force=True)
            raise
        # surface the dead incarnation's black box (if it got one out)
        # so post-mortem tooling finds it next to the recovered WAL
        from ..observability import flight as _flight
        dumps = _flight.find_dumps(wal_dir)
        if dumps:
            sup.last_flight_dump = dumps[-1]
        return sup

    def _install_recovered(self, state: Dict, t0: int = 0) -> None:
        """Apply a :func:`~paddle_tpu.serving.wal.recover_state` fold:
        validate geometry, install the PRNG snapshot, requeue every
        live session (durable journal entries — only future deltas
        append)."""
        geo = state.get("geometry")
        cache = self.engine.cache
        if geo is not None:
            for knob in ("page_size", "max_len", "max_batch"):
                if geo.get(knob) is not None \
                        and geo[knob] != getattr(cache, knob):
                    raise ValueError(
                        f"recover_from_disk: journaled {knob}="
                        f"{geo[knob]} does not match the fresh "
                        f"engine's {getattr(cache, knob)} — the "
                        f"factory must rebuild the dead engine's "
                        f"geometry")
            kv = (str(np.dtype(cache.kv_dtype))
                  if cache.kv_dtype is not None else None)
            if geo.get("kv_dtype") != kv:
                raise ValueError(
                    f"recover_from_disk: journaled kv_dtype="
                    f"{geo.get('kv_dtype')} != engine kv_dtype={kv}")
            draft = _draft_identity(self.engine)
            if geo.get("draft") != draft:
                raise ValueError(
                    f"recover_from_disk: journaled draft identity="
                    f"{geo.get('draft')} != engine {draft} — the "
                    f"factory must rebuild the same draft_layers/"
                    f"spec_tree (the draft pool itself rebuilds cold)")
        key_data = state.get("key_data")
        if key_data is not None and key_data.size:
            import jax
            import jax.numpy as jnp
            self._key_data = np.asarray(key_data)
            self.engine._key = jax.random.wrap_key_data(
                jnp.asarray(key_data))
        if state.get("prefix") is not None:
            # checkpoint_prefix payload: write the trie pages back
            # into the fresh pool FIRST, so the session replays below
            # (and future admissions) hit the restored prefix cache —
            # the same ordering restore() uses
            cache.restore_prefix(state["prefix"])
        self._next_rid = max(self._next_rid,
                             int(state.get("next_rid", 0)))
        self.engine._next_rid = max(self.engine._next_rid,
                                    self._next_rid)
        report = state.get("report", {})
        self.restored = {}
        for rid in sorted(state.get("sessions", {})):
            trs = _obs.serving_trace_now()
            rec = state["sessions"][rid]
            req = _session_from_record(self, rec,
                                       grammars=state.get("grammars"))
            self.journal.adopt(req, rec, durable=True)
            # requeue attaches the trace; the replay span lands after
            # so the recovered handle actually records it
            self.scheduler.requeue(req)
            if trs:
                _obs.serving_trace_span(
                    req, "wal_replay", trs,
                    replica=self.replica_id, seq=len(req.tokens))
            self.restored[req.rid] = req
        _obs.serving_wal_recovery(
            t0, len(self.restored),
            int(report.get("replayed_records", 0)),
            int(report.get("torn_tail_truncated", 0)),
            int(report.get("corrupt_quarantined", 0))
            + int(report.get("ckpt_quarantined", 0)))

    # ---- introspection ----
    def load_stats(self) -> Dict:
        """The scheduler's structured load snapshot
        (:meth:`~paddle_tpu.serving.ServingScheduler.load_stats`) plus
        the supervisor's own health/draining state — the per-replica
        signal the cluster router dispatches by."""
        s = (self.scheduler.load_stats()
             if self.scheduler is not None else {
                 "queue_depths": {}, "queued_total": 0,
                 "queued_tokens": 0, "inflight_tokens": 0, "running": 0,
                 "pending_prefills": 0, "free_slots": 0,
                 "oldest_deadline_slack_s": None, "pool_occupancy": 1.0,
                 "pool_free_pages": 0,
                 "degraded_level": len(DEGRADED_MODES),
                 "degraded_mode": "dead"})
        s["health"] = self.health
        s["draining"] = self._draining
        if self.wal is not None:
            # durable-plane lag signal (ISSUE 15): how far the on-disk
            # journal trails host state — a router/autoscaler can keep
            # crash-exposure bounded the same way it reads backlog
            s["wal"] = self.wal.stats()
        return s

    def stats(self) -> Dict:
        s = self.scheduler.stats() if self.scheduler is not None else {}
        s.update({
            "health": self.health,
            "degraded_level": self.degraded_level,
            "degraded_mode": self.degraded_mode,
            "recoveries": self.recoveries,
            "injected_faults": self.injected_faults,
            "real_faults": self.real_faults,
            "shed_total": self.shed_total,
            "supervised_steps": self.steps_total,
            "journal_entries": self.journal.size,
            "journal_tokens": self.journal.token_count,
        })
        return s
