"""Crash-durable serving plane (ISSUE 15): the on-disk write-ahead log
under :class:`~paddle_tpu.serving.RequestJournal`.

Every recovery guarantee the stack already carries (ISSUE 8 supervisor
rebuild, ISSUE 9 failover, ISSUE 13 integrity/retry) assumes the Python
process survives the fault: the request journal is host-memory only, so
a ``kill -9``, OOM-kill or host reboot loses every live session. This
module moves the source of truth to disk:

- :class:`WriteAheadLog` — a SEGMENTED append-only log of CRC-framed
  JSON records (``MAGIC | payload_len | crc32 | payload``). Admission
  params land on disk at submit time (write-ahead), per-step committed
  tokens / PRNG-key snapshots / adapter pins / constraint state /
  preempt-swap-handoff ownership transitions land at each journal sync.
  The fsync ladder is configurable: ``"commit"`` fsyncs every append
  (hard durability — an acked submission survives host power loss;
  highest overhead), ``"group"`` flushes every append to the OS and
  fsyncs at commit boundaries amortized over ``group_interval_s`` (the
  classic group-commit window, default 250 ms: state survives PROCESS
  death immediately and host power loss up to one window behind —
  measured < 5% step overhead by the ``decode_durability_overhead``
  bench rider), ``"off"`` flushes to the OS only. A failed append
  ROLLS BACK the file to the last frame boundary, so only real process
  death can leave a torn tail.

- **incremental checkpoints** — :meth:`WriteAheadLog.checkpoint` writes
  the journal snapshot as one atomic ``ckpt-<lsn>.npz`` (the PR 8
  drain/restore ``.npz`` machinery, stamped with the PR 13 per-array
  CRC convention) WITHOUT stopping admissions, then prunes every log
  segment the checkpoint fully covers — recovery is snapshot +
  log-suffix replay, so the log never grows with served traffic.

- :func:`recover_state` — the cold-restart scanner: picks the newest
  VALID checkpoint (corrupt/torn ones quarantine, counted; a checkpoint
  claiming an LSN the log never reached is a foreign/stale artifact and
  quarantines too), truncates a torn WAL tail at the last valid frame,
  quarantines any segment past a corrupt mid-log frame (replaying past
  a hole would install wrong state), and folds the surviving records
  into per-session state for
  :meth:`~paddle_tpu.serving.EngineSupervisor.recover_from_disk`.

Fault sites (ISSUE 8 discipline): ``wal_append`` fires BEFORE a frame
is written (nothing commits), ``wal_fsync`` before the fsync,
``checkpoint_write`` before the checkpoint file is produced. The
``wal_append`` TAMPER mode writes half a frame and latches the log dead
— the honest simulation of a process dying mid-write, exercised by the
crash-point sweep (tools/chaos_soak.py --crash, tests/test_wal.py).
"""
from __future__ import annotations

import base64
import json
import os
import struct
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observability import hooks as _obs
from .resilience import (InjectedFault, _np_dtype, fault_point,
                         payload_checksums, tamper_point,
                         verify_checksums)

#: frame header: magic, payload length, payload crc32
MAGIC = b"PTWL"
_HDR = struct.Struct("<4sII")

FSYNC_POLICIES = ("commit", "group", "off")


class WalTorn(RuntimeError):
    """The log latched dead after a simulated torn write (the
    ``wal_append`` tamper mode models a process dying mid-frame — a
    'process' that kept appending after its own death would be a
    simulation bug, so further appends raise this loudly)."""


def _seg_name(start_lsn: int) -> str:
    return f"wal-{start_lsn:016d}.log"


def _ckpt_name(lsn: int) -> str:
    return f"ckpt-{lsn:016d}.npz"


def _encode_frame(record: Dict) -> bytes:
    data = json.dumps(record, separators=(",", ":")).encode()
    return _HDR.pack(MAGIC, len(data), zlib.crc32(data) & 0xFFFFFFFF) \
        + data


class WriteAheadLog:
    """Segmented CRC-framed append-only log + incremental checkpoints.

    ``path`` is one journal directory (one per supervisor; the cluster
    gives each replica its own — ``replica<i>/`` — so a replacement
    replica can adopt a dead one's log). Records are JSON dicts stamped
    with a monotonically increasing ``lsn``; opening an existing
    directory scans it (tolerantly — repair belongs to
    :func:`recover_state`) and continues the sequence in a FRESH
    segment, so two generations of one replica never interleave frames
    in one file.
    """

    def __init__(self, path: str, *, fsync: str = "group",
                 segment_bytes: int = 1 << 20,
                 group_interval_s: float = 0.25,
                 clock=time.monotonic,
                 last_lsn: Optional[int] = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"WriteAheadLog: fsync={fsync!r} not in "
                f"{FSYNC_POLICIES}")
        self.path = path
        self.fsync = fsync
        self.segment_bytes = int(segment_bytes)
        self.group_interval_s = float(group_interval_s)
        self._clock = clock
        os.makedirs(path, exist_ok=True)
        if last_lsn is not None:
            # the caller just ran recover_state() on this directory
            # (repaired + scanned): trust its lsn instead of reading
            # the whole log a second time — recovery MTTR pays the
            # scan once
            self._lsn = int(last_lsn)
        else:
            # repair at open (the classic redo-log rule): a torn tail
            # from a prior crash truncates NOW, before this generation
            # appends — otherwise valid new segments would sit beyond
            # the tear and a later recovery scan would have to
            # quarantine them
            _records, report = scan_segments(path, repair=True)
            self._lsn = report["last_lsn"]
        self._f = None
        self._seg_path: Optional[str] = None
        self._dirty = False           # bytes flushed but not fsynced
        self._last_fsync = -1e9
        self._last_delta = -1e9
        self._torn = False
        self.appends_total = 0
        self.bytes_total = 0
        self.fsyncs_total = 0
        self.checkpoints_total = 0
        self.segments_pruned_total = 0
        #: host nanoseconds spent appending / fsyncing — the bench
        #: rider's wal_ms_per_step numerator
        self.append_ns = 0
        self.fsync_ns = 0

    # ---- segment management ----
    def _open_segment(self):
        self._seg_path = os.path.join(self.path,
                                      _seg_name(self._lsn + 1))
        self._f = open(self._seg_path, "ab")

    def _ensure_segment(self, frame_len: int):
        if self._f is None:
            self._open_segment()
            return
        if self._f.tell() + frame_len > self.segment_bytes \
                and self._f.tell() > 0:
            # rotate — fsync the retiring segment first so a pruned-
            # or-recovered log never depends on an unfsynced old file
            if self.fsync != "off" and self._dirty:
                self._fsync()
            self._f.close()
            self._open_segment()

    # ---- append / commit ----
    def append(self, kind: str, payload: Dict,
               flush: bool = False) -> int:
        """Append one record; returns its lsn. The fault site fires
        BEFORE anything is written (a fault commits nothing), and any
        write failure rolls the file back to the previous frame
        boundary — torn tails come only from process death (or the
        tamper simulation of one). Writes land in the userspace buffer
        and reach the OS at the next :meth:`commit` boundary (per-step)
        — ``flush=True`` pushes them now, the ACK path for write-ahead
        submit records (survives process death immediately; the fsync
        ladder governs power-loss durability on top)."""
        if self._torn:
            raise WalTorn(
                "WriteAheadLog: log latched dead after a simulated "
                "torn write — recover_state() owns this directory now")
        fault_point("wal_append")
        t0 = time.perf_counter_ns()
        rec = dict(payload)
        rec["lsn"] = self._lsn + 1
        rec["kind"] = kind
        frame = _encode_frame(rec)
        self._ensure_segment(len(frame))
        pos = self._f.tell()
        if tamper_point("wal_append"):
            # torn-write simulation: half a frame reaches the OS, then
            # the 'process dies'. The log object is unusable from here;
            # recovery must truncate the tail at the last valid frame.
            self._f.write(frame[:max(1, len(frame) // 2)])
            self._f.flush()
            self._torn = True
            raise InjectedFault(
                "wal_append", "tamper",
                "torn frame write (simulated mid-append process death)")
        try:
            self._f.write(frame)
            if flush or self.fsync == "commit":
                self._f.flush()
        except BaseException:
            try:
                self._f.seek(pos)
                self._f.truncate()
            except OSError:
                pass
            raise
        self._lsn = rec["lsn"]
        self._dirty = True
        self.appends_total += 1
        self.bytes_total += len(frame)
        self.append_ns += time.perf_counter_ns() - t0
        _obs.serving_wal_append(t0, len(frame))
        if self.fsync == "commit":
            self._fsync()
        return self._lsn

    def commit(self, force: bool = False) -> bool:
        """The group-commit boundary (one call per engine step): flush
        buffered frames to the OS (they now survive process death);
        under the ``"group"`` policy additionally fsync when the
        amortization window lapsed (``group_interval_s``; 0 = every
        boundary). ``force`` fsyncs regardless of policy/window — the
        drain/close path. Returns True when an fsync actually ran."""
        if not self._dirty or self._f is None:
            return False
        self._f.flush()
        if force or (self.fsync == "group"
                     and (self._clock() - self._last_fsync
                          >= self.group_interval_s)):
            self._fsync()
            return True
        return False

    def delta_due(self) -> bool:
        """Is a step-delta append pass due? Under ``"commit"`` (or a
        zero window) every step appends; under ``"group"``/``"off"``
        the per-step deltas batch on the SAME cadence as the group
        fsync window — they are not durable until the fsync anyway, so
        appending them sooner only pays frame cost for the same loss
        window. Submit records ignore this (write-ahead is per-ack);
        the journal buffers finish tombstones until the next due
        pass."""
        return (self.fsync == "commit" or self.group_interval_s <= 0
                or (self._clock() - self._last_delta
                    >= self.group_interval_s))

    def mark_delta(self) -> None:
        self._last_delta = self._clock()

    def _fsync(self):
        fault_point("wal_fsync")
        t0 = time.perf_counter_ns()
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False
        self._last_fsync = self._clock()
        self.fsyncs_total += 1
        self.fsync_ns += time.perf_counter_ns() - t0
        _obs.serving_wal_fsync(t0)

    def close(self):
        if self._f is not None:
            try:
                if self._dirty and self.fsync != "off":
                    self._fsync()
            except Exception:
                pass
            self._f.close()
            self._f = None

    # ---- checkpoints ----
    def checkpoint(self, meta: Dict,
                   arrays: Optional[Dict[str, np.ndarray]] = None
                   ) -> str:
        """Write one incremental checkpoint ``ckpt-<lsn>.npz`` (atomic
        tmp+rename; the drain ``.npz`` shape with per-array CRCs) and
        PRUNE: log segments whose every record the checkpoint covers
        are deleted, as are superseded checkpoint files (the newest
        previous one is kept as a fallback against a torn write of
        this one). Admissions never stop — this is one host-side call
        between steps, not a drain."""
        fault_point("checkpoint_write")
        t0 = time.perf_counter_ns()
        meta = dict(meta)
        meta["wal_lsn"] = self._lsn
        arrays = dict(arrays or {})
        meta["checksums"] = payload_checksums(arrays)
        fn = os.path.join(self.path, _ckpt_name(self._lsn))
        tmp = fn + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, meta=np.frombuffer(
                json.dumps(meta).encode(), np.uint8), **arrays)
        os.replace(tmp, fn)
        self.checkpoints_total += 1
        pruned = self._prune(self._lsn, keep_ckpt=fn)
        _obs.serving_wal_checkpoint(t0, os.path.getsize(fn),
                                    len(meta.get("sessions", ())),
                                    pruned)
        return fn

    def _prune(self, ckpt_lsn: int, keep_ckpt: str) -> int:
        """Compact: drop superseded checkpoints (keeping the new one
        plus ONE fallback), then delete log segments fully covered by
        the OLDEST KEPT checkpoint — not the newest. The fallback
        checkpoint is only a fallback if its log suffix still exists:
        pruning to the newest checkpoint's lsn would leave a gap
        behind the older one, and a recovery that had to fall back
        (the newest ``.npz`` torn by a crash mid-write) would
        resurrect finished sessions from pre-gap state. A segment
        named for its first lsn is covered when the NEXT segment
        starts at or below ``boundary + 1``."""
        cks = sorted(f for f in os.listdir(self.path)
                     if f.startswith("ckpt-") and f.endswith(".npz"))
        for old in cks[:-2]:        # keep the new one + one fallback
            if os.path.join(self.path, old) != keep_ckpt:
                try:
                    os.unlink(os.path.join(self.path, old))
                except OSError:
                    pass
        kept = sorted(int(f[5:-4]) for f in os.listdir(self.path)
                      if f.startswith("ckpt-") and f.endswith(".npz"))
        boundary = min(kept) if kept else ckpt_lsn
        pruned = 0
        segs = sorted(f for f in os.listdir(self.path)
                      if f.startswith("wal-") and f.endswith(".log"))
        starts = [int(s[4:-4]) for s in segs]
        for i, s in enumerate(segs):
            nxt = starts[i + 1] if i + 1 < len(starts) else None
            full = os.path.join(self.path, s)
            if (nxt is not None and nxt <= boundary + 1
                    and full != self._seg_path):
                try:
                    os.unlink(full)
                    pruned += 1
                except OSError:
                    pass
        self.segments_pruned_total += pruned
        return pruned

    @property
    def lsn(self) -> int:
        return self._lsn

    def stats(self) -> Dict:
        return {"lsn": self._lsn, "fsync_policy": self.fsync,
                "appends_total": self.appends_total,
                "bytes_total": self.bytes_total,
                "fsyncs_total": self.fsyncs_total,
                "checkpoints_total": self.checkpoints_total,
                "segments_pruned_total": self.segments_pruned_total,
                "append_ms_total": round(self.append_ns / 1e6, 3),
                "fsync_ms_total": round(self.fsync_ns / 1e6, 3)}


# ---------------- cold-restart scan / recovery ----------------

def scan_segments(path: str, repair: bool = True
                  ) -> Tuple[List[Dict], Dict]:
    """Read every frame from every segment in lsn order. A torn TAIL
    (short header/payload at end of the last written data) truncates at
    the last valid frame when ``repair`` is set; a corrupt frame with
    live data after it (bit-flip, foreign bytes) stops the scan there —
    records past a hole cannot be replayed safely — and quarantines the
    remainder (the tail of that segment truncates, later whole segments
    rename to ``.quarantined``). Returns ``(records, report)`` with
    ``report = {last_lsn, torn_tail_truncated, corrupt_quarantined}``.
    """
    records: List[Dict] = []
    report = {"last_lsn": 0, "torn_tail_truncated": 0,
              "corrupt_quarantined": 0}
    if not os.path.isdir(path):
        return records, report
    segs = sorted(f for f in os.listdir(path)
                  if f.startswith("wal-") and f.endswith(".log"))
    stop = None                     # index of the segment that broke
    for i, seg in enumerate(segs):
        full = os.path.join(path, seg)
        with open(full, "rb") as f:
            data = f.read()
        pos = 0
        bad_at = None
        torn = False
        while pos < len(data):
            if pos + _HDR.size > len(data):
                bad_at, torn = pos, True    # torn header at the tail
                break
            magic, ln, crc = _HDR.unpack_from(data, pos)
            if pos + _HDR.size + ln > len(data):
                bad_at, torn = pos, True    # torn payload at the tail
                break
            body = data[pos + _HDR.size: pos + _HDR.size + ln]
            if magic != MAGIC \
                    or (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                bad_at = pos        # corrupt frame (bit-flip/foreign)
                break
            try:
                rec = json.loads(body.decode())
            except Exception:
                bad_at = pos
                break
            records.append(rec)
            report["last_lsn"] = max(report["last_lsn"],
                                     int(rec.get("lsn", 0)))
            pos += _HDR.size + ln
        if bad_at is not None:
            if torn:
                report["torn_tail_truncated"] += 1
            else:
                report["corrupt_quarantined"] += 1
            if repair:
                with open(full, "r+b") as f:
                    f.truncate(bad_at)
                _obs.serving_integrity("wal", "quarantined")
            stop = i
            break
    if stop is not None and stop + 1 < len(segs):
        # whole segments past the hole: replaying them would skip the
        # lost records — never install that state
        for seg in segs[stop + 1:]:
            report["corrupt_quarantined"] += 1
            if repair:
                full = os.path.join(path, seg)
                try:
                    os.replace(full, full + ".quarantined")
                except OSError:
                    pass
                _obs.serving_integrity("wal", "quarantined")
    return records, report


def _load_checkpoint(path: str, fn: str) -> Optional[Dict]:
    """Decode + verify one checkpoint file; None when torn/corrupt."""
    full = os.path.join(path, fn)
    try:
        with np.load(full) as data:
            meta = json.loads(bytes(data["meta"]).decode())
            arrays = {n: np.asarray(data[n]) for n in data.files
                      if n != "meta"}
        verify_checksums(arrays, meta.get("checksums"), "wal_ckpt")
    except Exception:
        return None
    return {"meta": meta, "arrays": arrays, "file": full}


def _apply_delta(sessions: Dict, rec: Dict) -> None:
    """Fold one per-session step delta (or batched-frame entry) into
    the recovery state; an entry carrying ``fin`` retires the session
    (its results live on the caller's handle — nothing to recover)."""
    rid = int(rec["rid"])
    if rec.get("fin") is not None:
        sessions.pop(rid, None)
        return
    s = sessions.get(rid)
    if s is None:
        return                      # finished before a stray delta
    s["tokens"] = list(s.get("tokens") or ()) \
        + list(rec.get("toks") or ())
    for k in ("preemptions", "swapped", "admitted"):
        if k in rec:
            s[k] = rec[k]
    if rec.get("cstate") is not None \
            and s.get("constraint") is not None:
        s["constraint"] = dict(s["constraint"], **rec["cstate"])


def recover_state(path: str, repair: bool = True) -> Dict:
    """The cold-restart recovery scan: newest valid checkpoint + WAL
    suffix replay, folded into per-session state.

    Returns ``{"sessions": {rid: rec}, "next_rid", "key_data",
    "geometry", "report"}`` where each session rec matches the
    :meth:`~paddle_tpu.serving.resilience.JournalEntry.as_record`
    shape. ``report`` carries the media-fault counters
    (torn/quarantined frames, quarantined checkpoints) — the integrity
    gate's evidence that nothing corrupt was installed."""
    state: Dict = {"sessions": {}, "next_rid": 0, "key_data": None,
                   "geometry": None, "grammars": {}}
    records, report = scan_segments(path, repair=repair)
    report["ckpt_quarantined"] = 0
    ckpt_lsn = 0
    if os.path.isdir(path):
        cks = sorted((f for f in os.listdir(path)
                      if f.startswith("ckpt-") and f.endswith(".npz")),
                     reverse=True)
    else:
        cks = []
    for fn in cks:
        ck = _load_checkpoint(path, fn)
        stale = (ck is not None
                 and int(ck["meta"].get("wal_lsn", 0))
                 > report["last_lsn"] and records)
        if ck is not None and not stale:
            # log-suffix CONTINUITY: lsns are dense, so if any record
            # follows this checkpoint, the first one must be exactly
            # ckpt_lsn + 1 — a larger first lsn means the suffix was
            # pruned against a NEWER checkpoint that is now unusable,
            # and replaying across the gap would install stale state
            L = int(ck["meta"].get("wal_lsn", 0))
            after = [int(r.get("lsn", 0)) for r in records
                     if int(r.get("lsn", 0)) > L]
            if after and min(after) != L + 1:
                stale = True
        if ck is None or stale:
            # torn/corrupt — or claiming an lsn this log never wrote
            # (a foreign/stale checkpoint next to a regressed log):
            # quarantine, counted, and fall back to the next older
            # checkpoint (or pure log replay)
            report["ckpt_quarantined"] += 1
            _obs.serving_integrity("wal_ckpt", "quarantined")
            if repair:
                try:
                    os.replace(os.path.join(path, fn),
                               os.path.join(path, fn + ".quarantined"))
                except OSError:
                    pass
            continue
        meta = ck["meta"]
        ckpt_lsn = int(meta.get("wal_lsn", 0))
        state["next_rid"] = int(meta.get("next_rid", 0))
        state["geometry"] = {k: meta.get(k) for k in
                             ("page_size", "max_len", "max_batch",
                              "kv_dtype", "constraints", "draft")}
        kd = ck["arrays"].get("key_data")
        if kd is not None and kd.size:
            state["key_data"] = kd
        state["grammars"].update(meta.get("grammars") or {})
        pf = meta.get("prefix")
        if pf:
            # checkpoint_prefix=True carried the trie's structure AND
            # page KV bytes (raw-uint8, the drain .npz convention):
            # decode them into the restore_prefix shape so the cold
            # restart serves the persisted chains as prefix HITS
            state["prefix"] = {
                "page_ids": pf["page_ids"],
                "records": pf["records"],
                "arrays": {
                    n: np.frombuffer(
                        bytes(ck["arrays"][f"prefix_{n}"]),
                        _np_dtype(pf["dtypes"][n])
                    ).reshape(pf["shapes"][n])
                    for n in pf["shapes"]}}
        for rec in meta.get("sessions", ()):
            state["sessions"][int(rec["rid"])] = dict(rec)
        break
    replayed = 0
    for rec in records:
        if int(rec.get("lsn", 0)) <= ckpt_lsn:
            continue
        replayed += 1
        kind = rec.get("kind")
        if kind == "meta":
            state["geometry"] = {k: rec.get(k) for k in
                                 ("page_size", "max_len", "max_batch",
                                  "kv_dtype", "constraints", "draft")}
            state["next_rid"] = max(state["next_rid"],
                                    int(rec.get("next_rid", 0)))
        elif kind == "submit":
            rid = int(rec["rid"])
            state["sessions"][rid] = {
                k: rec.get(k) for k in
                ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "priority", "deadline_remaining_s", "tokens",
                 "admitted", "preemptions", "swapped", "adapter_id",
                 "constraint")}
            state["next_rid"] = max(state["next_rid"], rid + 1)
        elif kind == "step":
            _apply_delta(state["sessions"], rec)
        elif kind == "steps":
            # one batched frame per journal sync (the per-frame cost
            # amortization) — entries apply in order; "fin" retires
            for d in rec.get("entries", ()):
                _apply_delta(state["sessions"], d)
        elif kind == "grammar":
            # a shared DFA table, appended once per hash (sessions'
            # constraint records reference it by dfa_hash)
            state["grammars"][rec["hash"]] = rec["dfa"]
        elif kind in ("finish", "forget"):
            state["sessions"].pop(int(rec["rid"]), None)
        elif kind == "key":
            state["key_data"] = np.frombuffer(
                base64.b64decode(rec["data"]),
                _np_dtype(rec["dtype"])).reshape(rec["shape"])
    report["replayed_records"] = replayed
    report["ckpt_lsn"] = ckpt_lsn
    state["report"] = report
    return state
