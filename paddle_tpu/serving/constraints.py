"""Grammar / JSON-schema constrained decoding (ISSUE 14): compiled
token-level DFAs applied as per-row logit masks in the sampling step.

Free-form sampling cannot promise schema-valid output — production
traffic that feeds parsers (function calls, JSON APIs, SQL) either
retries on parse failure or post-hoc repairs. Constrained decoding
makes validity STRUCTURAL: a grammar compiles ONCE into a token-level
DFA (dense ``(states, vocab)`` transition table), each constrained row
carries a DFA state, the engine gathers each row's allowed-token mask
into the sampling step (``logits[~mask] = -inf`` before the
argmax/categorical — one ``where`` in the already-jitted program), and
the state advances at COMMIT with the token that actually landed. The
grammar machinery is pure host-side numpy; the device cost is one
``(B, vocab)`` bool operand per step.

Three compilation layers, cheapest first:

- :func:`dfa_from_sequences` — a trie DFA accepting exactly the given
  token sequences (closed answer sets, tool-name menus).
- :func:`dfa_from_regex` — a character-class regex (literals, ``|``,
  ``*``, ``+``, ``?``, ``()``, ``[a-z]`` classes, ``.``, escapes)
  compiled Thompson-style to an NFA, subset-constructed to a char DFA,
  then LIFTED to token level: token ``t`` transitions state ``s`` to
  the state reached by running ``t``'s string through the char DFA
  from ``s`` (tokens that die mid-string are masked out). The lift is
  what makes the per-step cost a table lookup instead of a parse.
- :func:`json_schema_dfa` — a restricted JSON-schema subset (objects
  with fixed properties: string / integer / boolean / enum) rendered
  to a regex in canonical key order and delegated to the regex
  compiler — schema-guaranteed output without a runtime parser.

Parity contract (the standing gate): masking can only EXCLUDE tokens,
so whenever the grammar admits the unconstrained argmax, constrained
greedy decode is TOKEN-IDENTICAL to unconstrained decode — gated in
tests/test_adapters.py, alongside the hard gate that every emitted
token is grammar-valid on every workload.
"""
from __future__ import annotations

import base64
from typing import Dict, List, Optional, Sequence

import numpy as np


class TokenDFA:
    """Dense token-level DFA: ``next[(state, token)]`` (-1 = reject),
    ``accepting[state]``. States are small ints; the per-state allowed
    mask is one vectorized compare, the per-token advance one lookup.

    The dense ``(states, vocab)`` table trades memory for a branch-free
    per-step mask gather — at serving vocab sizes (32–128k) one state
    row is a few hundred KB of host bools, built once per grammar."""

    def __init__(self, next_table: np.ndarray, accepting: np.ndarray,
                 start: int = 0):
        self._hash: Optional[str] = None
        self.next = np.asarray(next_table, np.int32)
        if self.next.ndim != 2:
            raise ValueError(
                f"TokenDFA: next_table must be (states, vocab), got "
                f"shape {self.next.shape}")
        self.accepting = np.asarray(accepting, bool).reshape(-1)
        if self.accepting.size != self.next.shape[0]:
            raise ValueError(
                f"TokenDFA: {self.accepting.size} accepting flags for "
                f"{self.next.shape[0]} states")
        self.start = int(start)

    @property
    def vocab(self) -> int:
        return self.next.shape[1]

    @property
    def num_states(self) -> int:
        return self.next.shape[0]

    def allowed(self, state: int) -> np.ndarray:
        """(vocab,) bool — tokens with a live transition from
        ``state``."""
        return self.next[state] >= 0

    def advance(self, state: int, token: int) -> int:
        """The successor state; -1 when ``token`` is not admitted."""
        return int(self.next[state, int(token)])

    def content_hash(self) -> str:
        """Stable identity of this grammar (sha1 of the table bytes):
        checkpoint/WAL records DEDUPE the dense table by it — many
        sessions sharing one grammar serialize the table once, and
        per-session records carry only the hash (ISSUE 15: at serving
        vocab sizes the table is MBs; re-encoding it per record would
        dominate every journal frame)."""
        if self._hash is None:
            import hashlib
            h = hashlib.sha1(np.ascontiguousarray(self.next).tobytes())
            h.update(np.packbits(self.accepting).tobytes())
            h.update(str(self.start).encode())
            self._hash = h.hexdigest()
        return self._hash

    def to_record(self) -> Dict:
        """JSON-able serialization of the dense table (ISSUE 15: the
        drain-checkpoint / WAL shape — base64 of the raw int32 table
        plus the accepting bitmap, so a mid-grammar session survives a
        drain or a cold restart with its grammar intact)."""
        return {
            "shape": list(self.next.shape),
            "table": base64.b64encode(
                np.ascontiguousarray(self.next).tobytes()).decode(),
            "accepting": base64.b64encode(
                np.packbits(self.accepting).tobytes()).decode(),
            "start": self.start,
        }

    @classmethod
    def from_record(cls, rec: Dict) -> "TokenDFA":
        shape = tuple(int(x) for x in rec["shape"])
        table = np.frombuffer(base64.b64decode(rec["table"]),
                              np.int32).reshape(shape)
        accepting = np.unpackbits(np.frombuffer(
            base64.b64decode(rec["accepting"]),
            np.uint8))[:shape[0]].astype(bool)
        return cls(table, accepting, start=int(rec.get("start", 0)))


class ConstraintState:
    """One request's live grammar state: the DFA, the current state id,
    and the violation counters the ``serving_constrain_*`` hooks read.

    The state advances at COMMIT time only (with the token that
    actually landed), so preempt→resume needs no replay-side handling:
    committed tokens are never re-sampled, and the host-side state
    object rides the request handle through evictions, swaps and
    requeues untouched. ``finished`` latches once eos lands."""

    def __init__(self, dfa: TokenDFA, eos_token_id: Optional[int] = None):
        self.dfa = dfa
        self.state = dfa.start
        self.eos_token_id = eos_token_id
        self.finished = False
        self.tokens_masked_total = 0
        self.dead_ends = 0

    def mask(self, vocab: int, eos_token_id=None) -> np.ndarray:
        """(vocab,) bool allowed-token mask for the CURRENT state: live
        DFA transitions, plus eos whenever the state is accepting (a
        complete grammar production may terminate). Fail-safe: a dead
        end (no live transition, not accepting) admits ONLY eos —
        counted, so a grammar hole terminates the stream instead of
        wedging the row — and a finished stream pins to eos (the
        engine's post-eos pad contract). On an EOS-LESS engine a state
        with no live transitions latches ``finished`` instead (the
        stream cannot terminate, so the tail free-runs unconstrained
        rather than crashing the commit)."""
        eos = (self.eos_token_id if eos_token_id is None
               else eos_token_id)
        m = np.zeros((vocab,), bool)
        if self.finished:
            if eos is not None:
                m[int(eos)] = True
            else:
                m[:] = True
            return m
        allowed = self.dfa.allowed(self.state)
        m[:allowed.size] |= allowed[:vocab]
        if self.dfa.accepting[self.state] and eos is not None:
            m[int(eos)] = True
        if not m.any():
            if not self.dfa.accepting[self.state]:
                self.dead_ends += 1
            if eos is not None:
                m[int(eos)] = True
            else:
                # no live transition and no terminator to emit: the
                # grammar can constrain nothing further (a COMPLETED
                # production on an eos-less engine, or a counted
                # grammar hole) — latch finished so the commit-time
                # advance tolerates the free-running tail instead of
                # raising on it
                self.finished = True
                m[:] = True
        self.tokens_masked_total += int(vocab - m.sum())
        return m

    def advance(self, token: int) -> None:
        """Fold one COMMITTED token into the state. Eos from an
        accepting (or dead-end) state finishes the stream; any other
        inadmissible token is a masking bug and raises loudly."""
        if self.finished:
            return
        eos = self.eos_token_id
        if eos is not None and int(token) == int(eos):
            self.finished = True
            return
        nxt = self.dfa.advance(self.state, token)
        if nxt < 0:
            raise ValueError(
                f"constrained decode committed inadmissible token "
                f"{int(token)} from state {self.state} — the sampling "
                f"mask was not applied")
        self.state = nxt

    def to_record(self, grammars: Optional[Dict] = None) -> Dict:
        """Serialize the LIVE state (ISSUE 15): dense DFA table + the
        current state id + the violation counters, so drain/restore and
        cold-restart recovery re-attach an equivalent constraint — the
        standing drain() refusal for constrained sessions retires with
        this. ``grammars`` (hash → table record) dedupes the table:
        the record then carries only ``dfa_hash`` and the caller ships
        the shared dict once (checkpoint meta / WAL grammar records)."""
        rec = {"state": int(self.state),
               "eos_token_id": self.eos_token_id,
               "finished": bool(self.finished),
               "dead_ends": int(self.dead_ends),
               "tokens_masked_total": int(self.tokens_masked_total)}
        if grammars is None:
            rec["dfa"] = self.dfa.to_record()
        else:
            h = self.dfa.content_hash()
            grammars.setdefault(h, self.dfa.to_record())
            rec["dfa_hash"] = h
        return rec

    @classmethod
    def from_record(cls, rec: Dict,
                    grammars: Optional[Dict] = None) -> "ConstraintState":
        if "dfa" in rec:
            dfa_rec = rec["dfa"]
        else:
            h = rec.get("dfa_hash")
            dfa_rec = (grammars or {}).get(h)
            if dfa_rec is None:
                raise ValueError(
                    f"ConstraintState.from_record: grammar {h!r} is "
                    f"not in the supplied grammar table — the "
                    f"checkpoint/WAL record set is incomplete")
        st = cls(TokenDFA.from_record(dfa_rec),
                 eos_token_id=rec.get("eos_token_id"))
        st.state = int(rec.get("state", st.dfa.start))
        st.finished = bool(rec.get("finished", False))
        st.dead_ends = int(rec.get("dead_ends", 0))
        st.tokens_masked_total = int(rec.get("tokens_masked_total", 0))
        return st

    def state_record(self) -> Dict:
        """The cheap per-step delta (WAL ``cstate``): everything but
        the table — folded over the submit-time record at replay."""
        return {"state": int(self.state), "finished": bool(self.finished),
                "dead_ends": int(self.dead_ends),
                "tokens_masked_total": int(self.tokens_masked_total)}


def dfa_from_sequences(sequences: Sequence[Sequence[int]],
                       vocab: int) -> TokenDFA:
    """Trie DFA accepting EXACTLY the given token sequences (each leaf
    accepting). Closed answer sets — classification labels, tool-name
    menus — compile in one pass with states == trie nodes."""
    if not sequences:
        raise ValueError("dfa_from_sequences: need at least one sequence")
    children: List[Dict[int, int]] = [{}]
    accepting = [False]
    for seq in sequences:
        seq = [int(t) for t in np.asarray(seq, np.int64).reshape(-1)]
        if not seq:
            accepting[0] = True
            continue
        node = 0
        for t in seq:
            if not (0 <= t < vocab):
                raise ValueError(
                    f"dfa_from_sequences: token {t} outside vocab "
                    f"{vocab}")
            nxt = children[node].get(t)
            if nxt is None:
                children.append({})
                accepting.append(False)
                nxt = len(children) - 1
                children[node][t] = nxt
            node = nxt
        accepting[node] = True
    table = np.full((len(children), vocab), -1, np.int32)
    for s, kids in enumerate(children):
        for t, nxt in kids.items():
            table[s, t] = nxt
    return TokenDFA(table, np.asarray(accepting, bool))


# ---------------- character-regex → char DFA → token lift ----------------

_EPS = -1          # epsilon edge label in the NFA


def _parse_regex(pattern: str):
    """Recursive-descent regex parser → NFA fragment list.
    Supported: literals, escapes, ``.``, ``[a-z0-9_]`` classes (with
    ranges), grouping ``()``, alternation ``|`` and the ``* + ?``
    quantifiers — the working subset JSON-shaped grammars need."""
    pos = [0]
    n = len(pattern)
    # NFA as (transitions: list of dict char->set(states) + eps sets)
    trans: List[Dict] = []
    eps: List[set] = []

    def new_state() -> int:
        trans.append({})
        eps.append(set())
        return len(trans) - 1

    def add(s: int, ch: str, t: int):
        trans[s].setdefault(ch, set()).add(t)

    def peek():
        return pattern[pos[0]] if pos[0] < n else None

    def eat():
        c = pattern[pos[0]]
        pos[0] += 1
        return c

    def parse_class():
        """``[...]`` — returns the set of admitted characters."""
        chars = set()
        negate = False
        if peek() == "^":
            eat()
            negate = True
        while True:
            c = peek()
            if c is None:
                raise ValueError("unterminated character class")
            if c == "]":
                eat()
                break
            c = eat()
            if c == "\\":
                c = eat()
            if peek() == "-" and pos[0] + 1 < n \
                    and pattern[pos[0] + 1] != "]":
                eat()
                hi = eat()
                if hi == "\\":
                    hi = eat()
                for o in range(ord(c), ord(hi) + 1):
                    chars.add(chr(o))
            else:
                chars.add(c)
        if negate:
            universe = {chr(o) for o in range(32, 127)}
            chars = universe - chars
        return chars

    def atom():
        c = peek()
        if c == "(":
            eat()
            frag = alternation()
            if peek() != ")":
                raise ValueError("unbalanced parenthesis")
            eat()
            return frag
        s, t = new_state(), new_state()
        if c == "[":
            eat()
            for ch in parse_class():
                add(s, ch, t)
        elif c == ".":
            eat()
            for o in range(32, 127):
                add(s, chr(o), t)
        elif c == "\\":
            eat()
            add(s, eat(), t)
        else:
            add(s, eat(), t)
        return s, t

    def quantified():
        s, t = atom()
        while peek() in ("*", "+", "?"):
            q = eat()
            ns, nt = new_state(), new_state()
            eps[ns].add(s)
            eps[t].add(nt)
            if q in ("*", "?"):
                eps[ns].add(nt)
            if q in ("*", "+"):
                eps[t].add(s)
            s, t = ns, nt
        return s, t

    def concat():
        s, t = quantified()
        while peek() is not None and peek() not in ")|":
            s2, t2 = quantified()
            eps[t].add(s2)
            t = t2
        return s, t

    def alternation():
        s, t = concat()
        while peek() == "|":
            eat()
            s2, t2 = concat()
            ns, nt = new_state(), new_state()
            eps[ns] |= {s, s2}
            eps[t].add(nt)
            eps[t2].add(nt)
            s, t = ns, nt
        return s, t

    start, end = alternation()
    if pos[0] != n:
        raise ValueError(f"trailing regex at {pos[0]}: "
                         f"{pattern[pos[0]:]!r}")
    return trans, eps, start, end


class CharDFA:
    """Subset-constructed character DFA of a regex pattern — the
    intermediate the token lift runs strings through."""

    def __init__(self, pattern: str):
        trans, eps, start, end = _parse_regex(pattern)

        def closure(states):
            out = set(states)
            stack = list(states)
            while stack:
                s = stack.pop()
                for t in eps[s]:
                    if t not in out:
                        out.add(t)
                        stack.append(t)
            return frozenset(out)

        start_set = closure({start})
        index = {start_set: 0}
        self.table: List[Dict[str, int]] = [{}]
        self.accepting: List[bool] = [end in start_set]
        work = [start_set]
        while work:
            cur = work.pop()
            i = index[cur]
            chars: Dict[str, set] = {}
            for s in cur:
                for ch, targets in trans[s].items():
                    chars.setdefault(ch, set()).update(targets)
            for ch, targets in chars.items():
                nxt = closure(targets)
                j = index.get(nxt)
                if j is None:
                    index[nxt] = j = len(self.table)
                    self.table.append({})
                    self.accepting.append(end in nxt)
                    work.append(nxt)
                self.table[i][ch] = j

    def run(self, state: int, text: str) -> int:
        """Advance ``state`` through ``text``; -1 = dead."""
        for ch in text:
            state = self.table[state].get(ch, -1)
            if state < 0:
                return -1
        return state


def dfa_from_regex(pattern: str,
                   token_strings: Sequence[str]) -> TokenDFA:
    """Compile ``pattern`` to a char DFA and LIFT it to token level
    over ``token_strings`` (token id -> its decoded string; empty
    strings — pad/special ids — are never admitted). Token ``t`` is
    admitted from state ``s`` iff running its whole string through the
    char DFA from ``s`` stays alive; the successor is where it lands.
    One ``(char_states, vocab)`` table build per grammar, amortized
    over every request that carries it."""
    cd = CharDFA(pattern)
    vocab = len(token_strings)
    table = np.full((len(cd.table), vocab), -1, np.int32)
    for t, text in enumerate(token_strings):
        if not text:
            continue
        for s in range(len(cd.table)):
            table[s, t] = cd.run(s, text)
    return TokenDFA(table, np.asarray(cd.accepting, bool))


_JSON_STRING = r'"[a-zA-Z0-9_ \-]*"'
_JSON_INT = r"(0|-?[1-9][0-9]*)"
_JSON_BOOL = r"(true|false)"

_RX_META = set("\\()[]{}|*+?.")


def _rx_escape(text: str) -> str:
    """Escape regex metacharacters so ``text`` matches LITERALLY in
    the rendered grammar — schema keys and enum values are data, not
    pattern (an unescaped ``+`` in an enum would quantify, a ``.``
    would wildcard, a ``(`` would crash the compile)."""
    return "".join("\\" + c if c in _RX_META else c for c in text)


def json_schema_dfa(schema: Dict,
                    token_strings: Sequence[str]) -> TokenDFA:
    """Compile a RESTRICTED JSON schema to a token DFA: an object with
    fixed ``properties`` of type string / integer / boolean / enum,
    rendered in the schema's (canonical) key order and delegated to
    :func:`dfa_from_regex`. The subset is deliberately small — enough
    for tool-call/extraction payloads; richer schemas compose their
    own regex and call :func:`dfa_from_regex` directly."""
    if schema.get("type") != "object":
        raise ValueError(
            f"json_schema_dfa: only object schemas are supported, got "
            f"type={schema.get('type')!r}")
    props = schema.get("properties") or {}
    if not props:
        raise ValueError("json_schema_dfa: object schema has no "
                         "properties")
    parts = []
    for key, spec in props.items():
        if "enum" in spec:
            vals = "|".join(
                f'"{_rx_escape(v)}"' if isinstance(v, str)
                else _rx_escape(str(v))
                for v in spec["enum"])
            val = f"({vals})"
        elif spec.get("type") == "string":
            val = _JSON_STRING
        elif spec.get("type") == "integer":
            val = _JSON_INT
        elif spec.get("type") == "boolean":
            val = _JSON_BOOL
        else:
            raise ValueError(
                f"json_schema_dfa: unsupported property type "
                f"{spec!r} for key {key!r}")
        parts.append(f'"{_rx_escape(key)}":{val}')
    pattern = r"\{" + ",".join(parts) + r"\}"
    return json_schema_pattern_dfa(pattern, token_strings)


def json_schema_pattern_dfa(pattern: str,
                            token_strings: Sequence[str]) -> TokenDFA:
    """The regex half of :func:`json_schema_dfa`, exposed so callers
    with richer schemas can render their own pattern and share the
    lift."""
    return dfa_from_regex(pattern, token_strings)
