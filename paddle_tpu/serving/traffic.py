"""Trace-driven traffic harness: open-loop load generation + goodput-
under-SLO measurement for the serving cluster (ISSUE 13).

Everything before this module exercised the PR 9–12 cluster with
hand-shaped request lists — clean benchmarks, not production. This
module makes overload behavior a MEASURED, regression-gated quantity:

- :func:`synth_trace` — a seeded open-loop trace generator: tenant
  populations sharing page-aligned prefix families (each tenant's
  system prompt routes through the PR 9 affinity machinery), a
  non-homogeneous Poisson arrival process with DIURNAL modulation and
  a BURST window (the overload the autoscaler must absorb), and mixed
  priority / deadline / length distributions. Same seed + same params
  => byte-identical trace, every run.

- :class:`FakeClock` — the injectable clock every cluster component
  already accepts: the driver advances virtual time per step, so
  arrival dynamics, deadlines and TTFT measurement are deterministic
  and CPU-speed-independent (no wall-clock anywhere in the SLO math).

- :func:`run_trace` — the open-loop driver: submissions land when the
  virtual clock reaches their arrival stamp REGARDLESS of how the
  cluster is coping (open-loop is what makes overload visible — a
  closed loop would politely slow its own offered load), steps the
  cluster, watches every handle for its first committed token, and
  folds the outcomes into an :class:`SLOReport`.

- :class:`SLOReport` — first-class goodput-under-SLO metrics: p50/p99
  TTFT, p50/p99 per-token latency, deadline-met fraction, goodput
  (tokens of SLO-met requests per WALL second — the bench tier's
  headline) and the rejection split (ratelimit / infeasible /
  overload), plus the autoscaler's up/down event counts when one is
  attached.

The harness drives :class:`~paddle_tpu.serving.ServingCluster` (the
production surface) but accepts anything with ``submit``/``step`` —
tools/chaos_soak.py --traffic points it at an autoscaling cluster with
corruption + handoff faults armed, and bench.py's
``decode_slo_goodput`` tier records its report with provenance.
"""
from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import hooks as _obs
from .policy import Priority

#: finish reasons that mean the cluster DECLINED the request at a
#: door (no tokens owed) rather than serving or losing it
REJECTED_REASONS = ("rejected_ratelimit", "rejected_infeasible",
                    "rejected_overload")


class FakeClock:
    """Injectable monotonic clock (virtual seconds): the single time
    source for the trace driver, every scheduler deadline and every
    rate-limit window — advanced ONLY by :func:`run_trace`, so a run's
    SLO arithmetic is identical on a laptop and a TPU host."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t


@dataclasses.dataclass
class TraceRequest:
    """One trace entry: everything :meth:`ServingCluster.submit`
    needs, plus the open-loop arrival stamp (virtual seconds)."""
    arrival_s: float
    tenant: str
    prompt: np.ndarray
    max_new_tokens: int
    priority: int = int(Priority.NORMAL)
    deadline_s: Optional[float] = None
    #: the tenant's LoRA variant (ISSUE 14); 0 = the base model — a
    #: trace generated without an adapter population runs unchanged on
    #: adapter-less clusters
    adapter_id: int = 0


def synth_trace(seed: int = 0, *, duration_s: float = 4.0,
                base_rps: float = 6.0, tenants: int = 4,
                page_size: int = 8, prefix_pages: int = 2,
                vocab: int = 256,
                tail_tokens: tuple = (2, 10),
                new_tokens: tuple = (3, 8),
                burst_start_frac: float = 0.35,
                burst_frac: float = 0.25, burst_mult: float = 4.0,
                diurnal_amp: float = 0.5,
                deadline_frac: float = 0.6,
                deadline_s: tuple = (0.5, 2.0),
                priority_weights=(0.2, 0.6, 0.2),
                adapters: int = 0,
                adapter_zipf: float = 1.2,
                text: bool = False) -> List[TraceRequest]:
    """Generate a seeded open-loop trace.

    Arrivals draw from a non-homogeneous Poisson process by thinning:
    the instantaneous rate is ``base_rps`` modulated by one diurnal
    sine cycle over ``duration_s`` (amplitude ``diurnal_amp``) and
    multiplied by ``burst_mult`` inside the burst window
    (``[burst_start_frac, burst_start_frac + burst_frac] *
    duration_s``) — the compressed shape of a production day with one
    traffic spike. Each request belongs to one of ``tenants`` tenant
    populations, carries its tenant's page-aligned system prompt
    (``prefix_pages * page_size`` tokens — the shared prefix family)
    plus a unique tail of ``uniform(*tail_tokens)`` tokens, decodes
    ``uniform(*new_tokens)`` new tokens, draws its priority class from
    ``priority_weights`` (HIGH/NORMAL/LOW) and — with probability
    ``deadline_frac`` — a first-token deadline of
    ``uniform(*deadline_s)`` virtual seconds.

    ``adapters`` (ISSUE 14): size of the LoRA variant population. When
    > 0 each TENANT is assigned one ``adapter_id`` drawn
    Zipf(``adapter_zipf``)-weighted over ``1..adapters`` — the
    head-heavy popularity curve of real fine-tune fleets (a few hot
    variants pinned resident, a long cold tail that exercises the
    slot-reclaim/demote/promote path) — and every request of that
    tenant carries it, so the trace drives adapter affinity and slot
    residency through the same open-loop arrivals as everything else.
    0 (default) leaves every request on the base model.

    ``text`` (ISSUE 20): NON-REPETITIVE text mode. Every prompt —
    system prefix AND tail — is drawn WITHOUT REPLACEMENT from a
    Zipf-weighted token population (head-heavy marginals like natural
    prose, but no token ever occurs twice in one prompt), so an
    in-context n-gram lookup finds NOTHING to draft from by
    construction. This is the scoreboard workload for model-based
    draft/tree speculation: the prompt-lookup proposer's acceptance
    rounds to zero here while a draft model's does not — exactly the
    traffic where speculation pays most and PR 5's proposer pays
    least. Requires ``vocab >= prefix_pages*page_size +
    tail_tokens[1]``."""
    if duration_s <= 0 or base_rps <= 0:
        raise ValueError(
            f"synth_trace: duration_s={duration_s} and base_rps="
            f"{base_rps} must be > 0")
    if adapters < 0:
        raise ValueError(f"synth_trace: adapters={adapters} must be "
                         f">= 0")
    plen = prefix_pages * page_size
    if text and vocab - 3 < plen + tail_tokens[1]:
        raise ValueError(
            f"synth_trace: text mode needs vocab >= "
            f"{3 + plen + tail_tokens[1]} (prefix {plen} + tail "
            f"{tail_tokens[1]} distinct tokens), got {vocab}")
    rs = np.random.RandomState(seed)
    if text:
        # Zipf marginals over a seeded permutation of the usable ids
        # (so popularity is decoupled from token-id order), sampled
        # WITHOUT replacement per prompt — head-heavy like prose, but
        # zero in-context repetition for an n-gram lookup to find
        ids = rs.permutation(np.arange(3, vocab, dtype=np.int32))
        zw = np.arange(1, ids.size + 1, dtype=np.float64) ** -1.1
        zw /= zw.sum()
        sys_prompts = {
            t: rs.choice(ids, size=plen, replace=False, p=zw).astype(
                np.int32)
            for t in range(tenants)}
        tail_pool = {}
        for t in range(tenants):
            keep = ~np.isin(ids, sys_prompts[t])
            w = zw[keep]
            tail_pool[t] = (ids[keep], w / w.sum())
    else:
        sys_prompts = {
            t: rs.randint(3, vocab, (plen,)).astype(np.int32)
            for t in range(tenants)}
    tenant_adapter = {t: 0 for t in range(tenants)}
    if adapters:
        ranks = np.arange(1, adapters + 1,
                          dtype=np.float64) ** -adapter_zipf
        tenant_adapter = {
            t: int(rs.choice(np.arange(1, adapters + 1),
                             p=ranks / ranks.sum()))
            for t in range(tenants)}
    peak = base_rps * (1 + diurnal_amp) * max(1.0, burst_mult)

    def rate(t: float) -> float:
        r = base_rps * (1.0 + diurnal_amp
                        * math.sin(2 * math.pi * t / duration_s))
        b0 = burst_start_frac * duration_s
        if b0 <= t < b0 + burst_frac * duration_s:
            r *= burst_mult
        return max(r, 1e-6)

    out: List[TraceRequest] = []
    t = 0.0
    while True:
        # Poisson thinning against the constant majorant `peak`
        t += float(rs.exponential(1.0 / peak))
        if t >= duration_s:
            break
        if rs.random_sample() >= rate(t) / peak:
            continue
        tenant = int(rs.randint(tenants))
        nt = int(rs.randint(tail_tokens[0], tail_tokens[1] + 1))
        if text:
            pool, pw = tail_pool[tenant]
            tail = rs.choice(pool, size=nt, replace=False,
                             p=pw).astype(np.int32)
        else:
            tail = rs.randint(3, vocab, (nt,)).astype(np.int32)
        prio = int(rs.choice(
            [int(Priority.HIGH), int(Priority.NORMAL),
             int(Priority.LOW)], p=np.asarray(priority_weights)
            / sum(priority_weights)))
        dl = None
        if rs.random_sample() < deadline_frac:
            dl = float(rs.uniform(deadline_s[0], deadline_s[1]))
        out.append(TraceRequest(
            arrival_s=round(t, 6), tenant=f"tenant{tenant}",
            prompt=np.concatenate([sys_prompts[tenant], tail]),
            max_new_tokens=int(rs.randint(new_tokens[0],
                                          new_tokens[1] + 1)),
            priority=prio, deadline_s=dl,
            adapter_id=tenant_adapter[tenant]))
    return out


@dataclasses.dataclass
class SLOReport:
    """Goodput-under-SLO outcome of one :func:`run_trace` run."""
    requests: int = 0
    completed: int = 0
    rejected: Dict[str, int] = dataclasses.field(default_factory=dict)
    lost: int = 0
    deadline_met_fraction: float = 1.0
    p50_ttft_s: Optional[float] = None
    p99_ttft_s: Optional[float] = None
    p50_per_token_s: Optional[float] = None
    p99_per_token_s: Optional[float] = None
    goodput_tokens: int = 0
    badput_tokens: int = 0
    goodput_tokens_per_s: float = 0.0
    wall_s: float = 0.0
    virtual_s: float = 0.0
    steps: int = 0
    autoscale_up: int = 0
    autoscale_down: int = 0
    #: per-phase TTFT attribution (ISSUE 16) — {phase: {p50_ms,
    #: p99_ms}} over completed first-token requests, harvested from
    #: each handle's request trace; None unless tracing was enabled
    ttft_breakdown: Optional[Dict] = None

    def as_dict(self) -> Dict:
        d = dataclasses.asdict(self)
        for k in ("p50_ttft_s", "p99_ttft_s", "p50_per_token_s",
                  "p99_per_token_s"):
            if d[k] is not None:
                d[k] = round(d[k], 6)
        d["goodput_tokens_per_s"] = round(d["goodput_tokens_per_s"], 2)
        d["deadline_met_fraction"] = round(d["deadline_met_fraction"], 4)
        d["wall_s"] = round(d["wall_s"], 3)
        if d["ttft_breakdown"] is not None:
            d["ttft_breakdown"] = {
                ph: {q: round(v, 3) for q, v in pcts.items()}
                for ph, pcts in d["ttft_breakdown"].items()}
        return d


def run_trace(cluster, trace: List[TraceRequest], clock: FakeClock, *,
              step_dt: float = 0.02, max_steps: int = 100000,
              drain: bool = True, on_submit=None) -> SLOReport:
    """Drive ``trace`` through ``cluster`` open-loop and measure.

    Each iteration submits every arrival whose stamp the virtual clock
    has reached (open-loop: the offered load never waits for the
    cluster), steps the cluster once, scans the live handles for first
    tokens (TTFT is stamped the step the token appears, in virtual
    seconds), and advances the clock by ``step_dt``. With ``drain``
    the loop runs until every submitted request finished; without, it
    stops when the trace is exhausted and the cluster idles.

    The deadline SLO is the scheduler's own semantics (first-token):
    a deadline-bearing request MET its SLO iff it produced a first
    token by ``arrival + deadline``; deadline-less requests are met by
    completing. Rejections (ratelimit / infeasible / overload) are
    counted separately — they are the admission machinery doing its
    job — and never score as met, but also never as lost: ``lost``
    counts only requests that vanished without a structured reason,
    and the soak gates it at zero."""
    order = sorted(range(len(trace)),
                   key=lambda i: (trace[i].arrival_s, i))
    nxt = 0
    live: List[Dict] = []
    report = SLOReport(requests=len(trace))
    ttfts: List[float] = []
    per_tok: List[float] = []
    # per-phase TTFT rows (ISSUE 16): harvested from each COMPLETED
    # handle's own trace, so a shared tracer polluted by other runs
    # (or LRU aging) never skews this run's percentiles
    bd_rows: List[Dict] = []
    met = missed = 0
    # arrivals are RELATIVE to the clock at entry, so one cluster (and
    # its compiled programs) can serve a warm pass and a timed pass of
    # the same trace back to back — the bench tier's contract
    t_virt0 = clock()
    t_wall0 = time.perf_counter()
    auto = getattr(cluster, "autoscaler", None)
    up0 = auto.up_events if auto is not None else 0
    down0 = auto.down_events if auto is not None else 0

    def harvest(rec) -> bool:
        """Fold one finished (or first-token) handle observation."""
        req = rec["req"]
        if rec["first_s"] is None and req.tokens:
            rec["first_s"] = clock()
        if not req.done:
            return False
        return True

    while True:
        now = clock()
        while nxt < len(order) and \
                trace[order[nxt]].arrival_s <= now - t_virt0:
            tr = trace[order[nxt]]
            nxt += 1
            req = cluster.submit(
                tr.prompt, max_new_tokens=tr.max_new_tokens,
                tenant=tr.tenant, priority=tr.priority,
                deadline_s=tr.deadline_s,
                adapter_id=getattr(tr, "adapter_id", 0))
            if on_submit is not None:
                # the chaos soak's handle collector: invariants like
                # zero-lost/zero-duplicated need every request handle,
                # not just the aggregated report
                on_submit(tr, req)
            live.append({"req": req, "tr": tr, "arrival": now,
                         "first_s": None})
        more = cluster.step()
        report.steps += 1
        still = []
        for rec in live:
            if not harvest(rec):
                still.append(rec)
                continue
            req, tr = rec["req"], rec["tr"]
            reason = req.finish_reason
            ntok = len(req.tokens)
            if reason in REJECTED_REASONS or \
                    reason == "deadline_exceeded":
                # a structured decline (door rejection, or the
                # scheduler expired it before any token): the cluster
                # did its job — scored as an SLO miss, never as lost
                report.rejected[reason] = \
                    report.rejected.get(reason, 0) + 1
                missed += 1
                continue
            if reason is None or reason == "engine_dead":
                report.lost += 1
                continue
            report.completed += 1
            rtr = getattr(req, "trace", None)
            if rtr is not None:
                bd = rtr.ttft_breakdown()
                if bd is not None:
                    bd_rows.append(bd)
            ok = True
            if rec["first_s"] is not None:
                ttft = rec["first_s"] - rec["arrival"]
                ttfts.append(ttft)
                if tr.deadline_s is not None:
                    ok = ttft <= tr.deadline_s
                if ntok > 1:
                    per_tok.append(
                        (clock() - rec["first_s"]) / (ntok - 1))
                _obs.serving_slo_ttft(ttft, ok, tr.priority)
            elif tr.deadline_s is not None:
                # finished without any token (deadline_exceeded): the
                # SLO was missed by definition
                ok = False
            if ok:
                met += 1
                report.goodput_tokens += ntok
            else:
                missed += 1
                report.badput_tokens += ntok
            _obs.serving_slo_tokens(ntok, ok)
        live = still
        clock.advance(step_dt)
        if nxt >= len(order) and not live:
            break
        if nxt >= len(order) and not more and not drain:
            break
        if report.steps >= max_steps:
            raise RuntimeError(
                f"run_trace: trace did not drain within {max_steps} "
                f"steps ({len(live)} live, {len(order) - nxt} "
                f"unsubmitted)")
    for rec in live:    # drain=False leftovers: count, don't score
        report.lost += 1
    report.wall_s = time.perf_counter() - t_wall0
    report.virtual_s = clock() - t_virt0
    total_scored = met + missed
    report.deadline_met_fraction = (met / total_scored
                                    if total_scored else 1.0)
    report.goodput_tokens_per_s = (report.goodput_tokens
                                   / report.wall_s
                                   if report.wall_s > 0 else 0.0)
    if ttfts:
        report.p50_ttft_s = float(np.percentile(ttfts, 50))
        report.p99_ttft_s = float(np.percentile(ttfts, 99))
    if per_tok:
        report.p50_per_token_s = float(np.percentile(per_tok, 50))
        report.p99_per_token_s = float(np.percentile(per_tok, 99))
    if bd_rows:
        report.ttft_breakdown = {
            ph: {"p50_ms": float(np.percentile(
                     [r[ph] for r in bd_rows], 50)),
                 "p99_ms": float(np.percentile(
                     [r[ph] for r in bd_rows], 99))}
            for ph in ("queue_ms", "prefill_ms", "handoff_ms",
                       "swap_ms", "sched_overhead_ms", "ttft_ms")}
    if auto is not None:
        # THIS run's scaling activity (a warm pass on the same
        # cluster has its own events)
        report.autoscale_up = auto.up_events - up0
        report.autoscale_down = auto.down_events - down0
    _obs.serving_slo_report(
        report.goodput_tokens_per_s, report.deadline_met_fraction,
        report.p99_ttft_s * 1e3 if report.p99_ttft_s is not None
        else None)
    return report
