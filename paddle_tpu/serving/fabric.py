"""Shared content-addressed KV fabric (ISSUE 19).

The PR 10 standing prefix store (:class:`~paddle_tpu.serving.host_tier.
HostPageStore`) generalized into a CLUSTER-WIDE tier: one fabric server
process owns a ``HostPageStore`` (same LRU RAM bound, same standing
disk layer with byte-bounded oldest-mtime pruning) and any replica on
any host can DEMOTE payloads to it and PROMOTE payloads from it over
the :mod:`paddle_tpu.serving.rpc` frame protocol. The payloads are the
existing content-addressed byte conventions, unchanged:

- prefix chains keyed by the raw token bytes of the chain (so two
  replicas that prefill the same system prompt address the SAME fabric
  entry — content addressing is what makes the warm-start story work),
- swap payloads keyed by ``("swap", rid)``,
- adapter factors keyed by ``b"adapter/<id>"``.

:class:`FabricClient` duck-types the ``HostPageStore`` surface the
tiered cache consumes (``put`` / ``get`` / ``contains`` / ``pop`` /
``quarantine`` / ``stats``), so attaching a replica to the fabric is
one assignment — ``engine.cache.host = FabricClient.dial(...)`` — and
every existing host-tier path (preemption swap, prefix demote/promote
write-through, adapter demotion) transparently moves through the
cluster tier: a freshly scaled-up replica PROMOTES another replica's
demoted system prompt instead of cold-prefilling it.

Integrity (the ISSUE 13 discipline at the fabric hop): entries carry
their per-array CRC32 stamps end-to-end. The server verifies them
before installing a demoted payload; the client verifies them before
returning a promoted payload — a mismatch quarantines the entry on
the server (never re-served) and surfaces an honest MISS, so the
caller falls back to the gated replay path token-identically. Fabric
unavailability degrades the same way: a dead fabric makes every
lookup a miss and every demote a local no-op — the fabric is a cache,
losing it must never take serving down.

Fault sites (fire BEFORE any commit): ``fabric_put`` before a demote
ships, ``fabric_get`` before a promote fetch — plus the
``fabric_get`` TAMPER mode, which flips real payload bytes so the
CHECKSUM path (not the injector) detects the corruption.

Run a standalone fabric server with::

    python -m paddle_tpu.serving.fabric --dir /path/standing \
        --page-size 8 --port 0 --port-file /path/fabric.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Optional, Tuple

import numpy as np

from ..observability import hooks as _obs
from .host_tier import HostPageStore, _tampered_entry
from .resilience import (
    CorruptionDetected, fault_point, tamper_point, verify_checksums,
)
from .rpc import ReplicaUnreachable, RpcClient, RpcServer


# ---------------------------------------------------------------------------
# key / entry wire codecs


def key_to_wire(key) -> Dict:
    """Store key -> JSON-able form. The store's key universe is bytes
    (prefix chains, ``b"adapter/..."``), str, int and flat tuples of
    those (``("swap", rid)``)."""
    if isinstance(key, bytes):
        return {"t": "b", "v": key.hex()}
    if isinstance(key, str):
        return {"t": "s", "v": key}
    if isinstance(key, (int, np.integer)):
        return {"t": "i", "v": int(key)}
    if isinstance(key, tuple):
        return {"t": "t", "v": [key_to_wire(k) for k in key]}
    raise ValueError(f"fabric: unencodable store key {key!r}")


def key_from_wire(w: Dict):
    t = w["t"]
    if t == "b":
        return bytes.fromhex(w["v"])
    if t == "s":
        return w["v"]
    if t == "i":
        return int(w["v"])
    return tuple(key_from_wire(k) for k in w["v"])


def entry_to_wire(entry: Dict) -> Tuple[Dict, Dict]:
    """Arrays-bearing payload dict -> (JSON-able data, blob dict).
    Generic over every payload shape that follows the raw-uint8 +
    per-array-CRC32 convention — :meth:`HostPageStore.encode` store
    entries AND :meth:`PagedKVCache.export_request` handoff payloads:
    the ``arrays`` ride as RPC blobs, every other key is metadata
    (numpy scalars fold to ints in the frame encoder)."""
    data = {k: v for k, v in entry.items() if k != "arrays"}
    data["checksums"] = {k: int(v)
                         for k, v in (entry.get("checksums")
                                      or {}).items()}
    return data, dict(entry["arrays"])


def entry_from_wire(data: Dict, blobs: Dict) -> Dict:
    out = dict(data)
    out["arrays"] = dict(blobs)
    return out


# ---------------------------------------------------------------------------
# server


class FabricServer:
    """The fabric process: one :class:`HostPageStore` behind an
    :class:`RpcServer`. All policy — LRU RAM bound, standing disk
    layer, disk pruning, quarantine — is the store's own, unchanged;
    this class only moves entries on and off the wire and enforces the
    CRC gate on inbound payloads."""

    def __init__(self, page_size: int,
                 capacity_pages: Optional[int] = None,
                 path: Optional[str] = None,
                 max_disk_bytes: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = HostPageStore(page_size,
                                   capacity_pages=capacity_pages,
                                   path=path,
                                   max_disk_bytes=max_disk_bytes)
        self.rpc = RpcServer(self, host=host, port=port)
        self.quarantined_inbound = 0

    @property
    def port(self) -> int:
        return self.rpc.port

    def start(self) -> "FabricServer":
        self.rpc.start()
        return self

    def serve_forever(self) -> None:
        self.rpc.serve_forever()

    def shutdown(self) -> None:
        self.rpc.shutdown()

    # -- RPC surface ------------------------------------------------

    def rpc_ping(self, data, blobs):
        return {"ok": True, "pid": os.getpid(),
                "page_size": self.store.page_size}

    def rpc_put(self, data, blobs):
        key = key_from_wire(data["key"])
        entry = entry_from_wire(data, blobs)
        try:
            # the CRC gate: a payload corrupted between the client's
            # encode and here must never enter the shared store (the
            # frame CRC guards the hop, the entry CRCs guard end-to-end)
            verify_checksums(entry["arrays"], entry["checksums"],
                             "fabric_put")
        except CorruptionDetected:
            self.quarantined_inbound += 1
            self.store.quarantined_total += 1
            _obs.serving_integrity("fabric_put", "detected")
            _obs.serving_fabric_quarantine("fabric_put")
            raise
        self.store.put(key, HostPageStore.decode(entry),
                       extra=entry["extra"], persist=entry["persist"])
        return {"ok": True}

    def rpc_get(self, data, blobs):
        entry = self.store.get(key_from_wire(data["key"]),
                               touch=bool(data.get("touch", True)))
        if entry is None:
            return {"hit": False}
        out, oblobs = entry_to_wire(entry)
        out["hit"] = True
        return out, oblobs

    def rpc_contains(self, data, blobs):
        return {"hit": self.store.contains(key_from_wire(data["key"]))}

    def rpc_pop(self, data, blobs):
        return {"hit": self.store.pop(key_from_wire(data["key"]))
                is not None}

    def rpc_quarantine(self, data, blobs):
        self.store.quarantine(key_from_wire(data["key"]),
                              str(data.get("site", "fabric_get")))
        return {"ok": True}

    def rpc_stats(self, data, blobs):
        s = self.store.stats()
        s["quarantined_inbound"] = self.quarantined_inbound
        s["rpc_frames_served"] = self.rpc.frames_served
        return s

    def rpc_shutdown(self, data, blobs):
        # reply first, then close the listener from a fresh thread so
        # the dispatcher is not tearing down the socket it is answering
        # on
        import threading
        threading.Timer(0.05, self.shutdown).start()
        return {"ok": True}


# ---------------------------------------------------------------------------
# client


class FabricClient:
    """A replica's stub onto the fabric — duck-types the
    :class:`HostPageStore` surface :class:`~paddle_tpu.serving.
    host_tier.TieredKVCache` consumes, so ``engine.cache.host = client``
    routes every host-tier demote/promote through the cluster tier.

    Degradation contract: transport loss (:class:`ReplicaUnreachable`)
    NEVER propagates — a demote becomes a local no-op (the encoded
    entry is still returned so caller accounting holds), a promote or
    probe becomes an honest miss. CRC mismatches on promoted payloads
    quarantine server-side and also read as a miss, so every corrupt
    path funnels into the existing gated replay fallback."""

    def __init__(self, client: RpcClient, page_size: int):
        self._rpc = client
        self.page_size = int(page_size)
        # client-side mirror counters (the server's stats() is one RPC
        # away; these make local assertions and tier_stats cheap)
        self.puts_total = 0
        self.hits_total = 0
        self.misses_total = 0
        self.quarantined_total = 0
        self.unreachable_total = 0
        # the load_stats surface (scheduler.py reads these off the
        # host tier as a residency signal): this client's OWN
        # contribution to the shared store — the cluster-wide truth is
        # one stats() RPC away, too expensive for the per-dispatch
        # load snapshot
        self.pages_resident = 0
        self.bytes_resident = 0

    @classmethod
    def dial(cls, host: str, port: int, *, page_size: int,
             **kw) -> "FabricClient":
        kw.setdefault("label", "fabric")
        return cls(RpcClient.dial(host, port, **kw), page_size)

    def put(self, key, arrays: Dict[str, np.ndarray],
            extra: Optional[Dict] = None,
            persist: bool = False) -> Dict:
        fault_point("fabric_put")
        entry = HostPageStore.encode(arrays)
        entry["extra"] = dict(extra or {})
        entry["persist"] = bool(persist)
        data, blobs = entry_to_wire(entry)
        data["key"] = key_to_wire(key)
        t0 = _obs.generate_begin()
        try:
            self._rpc.call("put", data, blobs)
            self.puts_total += 1
            self.pages_resident += int(entry["pages"])
            self.bytes_resident += int(entry["bytes"])
            _obs.serving_fabric_demote(t0, entry["bytes"])
        except ReplicaUnreachable:
            self.unreachable_total += 1
        return entry

    def get(self, key, touch: bool = True) -> Optional[Dict]:
        fault_point("fabric_get")
        t0 = _obs.generate_begin()
        try:
            data, blobs = self._rpc.call(
                "get", {"key": key_to_wire(key), "touch": bool(touch)})
        except ReplicaUnreachable:
            self.unreachable_total += 1
            self.misses_total += 1
            _obs.serving_fabric_promote(t0, 0, False)
            return None
        if not data.get("hit"):
            self.misses_total += 1
            _obs.serving_fabric_promote(t0, 0, False)
            return None
        entry = entry_from_wire(data, blobs)
        if tamper_point("fabric_get"):
            # chaos: flip real payload bytes so the CRC verifier below
            # is what detects the corruption (ISSUE 13 tamper idiom)
            entry = _tampered_entry(entry)
        try:
            # verify BEFORE the entry reaches any caller install path —
            # a corrupt fabric payload must read as a miss, never as
            # bytes
            verify_checksums(entry["arrays"], entry["checksums"],
                             "fabric_get")
        except CorruptionDetected:
            self.quarantined_total += 1
            _obs.serving_integrity("fabric_get", "detected")
            _obs.serving_fabric_quarantine("fabric_get")
            self.quarantine(key, "fabric_get", _local=False)
            self.misses_total += 1
            _obs.serving_fabric_promote(t0, 0, False)
            return None
        self.hits_total += 1
        _obs.serving_fabric_promote(t0, entry["bytes"], True)
        return entry

    def contains(self, key) -> bool:
        try:
            data, _ = self._rpc.call("contains",
                                     {"key": key_to_wire(key)})
            return bool(data.get("hit"))
        except ReplicaUnreachable:
            self.unreachable_total += 1
            return False

    def __contains__(self, key) -> bool:
        return self.contains(key)

    def pop(self, key) -> Optional[Dict]:
        """Drop ``key`` fabric-side. Returns None — the tiered cache's
        call sites discard the popped entry, and shipping it back would
        move bytes nothing reads."""
        try:
            self._rpc.call("pop", {"key": key_to_wire(key)})
        except ReplicaUnreachable:
            self.unreachable_total += 1
        return None

    def quarantine(self, key, site: str, _local: bool = True) -> None:
        if _local:
            self.quarantined_total += 1
            _obs.serving_fabric_quarantine(site)
        try:
            self._rpc.call("quarantine",
                           {"key": key_to_wire(key), "site": site})
        except ReplicaUnreachable:
            self.unreachable_total += 1

    def stats(self) -> Dict:
        """Server-side store stats (one RPC), falling back to the
        client-side mirror when the fabric is unreachable."""
        try:
            data, _ = self._rpc.call("stats", {})
            data["client_hits_total"] = self.hits_total
            data["client_misses_total"] = self.misses_total
            data["client_unreachable_total"] = self.unreachable_total
            return data
        except ReplicaUnreachable:
            self.unreachable_total += 1
            return {"entries": -1, "pages_resident": 0,
                    "bytes_resident": 0, "capacity_pages": None,
                    "puts_total": self.puts_total,
                    "hits_total": self.hits_total,
                    "misses_total": self.misses_total,
                    "capacity_drops_total": 0,
                    "quarantined_total": self.quarantined_total,
                    "disk_pruned_total": 0,
                    "disk_pruned_bytes_total": 0,
                    "client_unreachable_total": self.unreachable_total}

    def close(self) -> None:
        self._rpc.close()


# ---------------------------------------------------------------------------
# worker-process entry


def write_endpoint_file(path: str, port: int) -> None:
    """Atomic ``{"port", "pid"}`` handshake file — the parent polls
    for it to learn the bound port (binding port 0 dodges races)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"port": int(port), "pid": os.getpid()}, f)
    os.replace(tmp, path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="paddle_tpu shared KV fabric server")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--page-size", type=int, default=8)
    p.add_argument("--capacity-pages", type=int, default=None)
    p.add_argument("--dir", default=None,
                   help="standing disk layer directory")
    p.add_argument("--max-disk-bytes", type=int, default=None)
    p.add_argument("--port-file", default=None,
                   help="write a {port, pid} JSON handshake here once "
                        "the listener is bound")
    args = p.parse_args(argv)
    srv = FabricServer(args.page_size,
                       capacity_pages=args.capacity_pages,
                       path=args.dir, max_disk_bytes=args.max_disk_bytes,
                       host=args.host, port=args.port)
    if args.port_file:
        write_endpoint_file(args.port_file, srv.port)
    srv.serve_forever()
    return 0


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    sys.exit(main())
