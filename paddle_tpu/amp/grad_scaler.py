"""GradScaler (reference: python/paddle/amp/grad_scaler.py — AmpScaler:62,
GradScaler:657). Dynamic loss scaling for fp16; bf16 paths typically run with
scaling disabled (TPU-native)."""
from __future__ import annotations

import enum
from typing import Dict

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core.autograd import no_grad


class OptimizerState(enum.Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._opt_states: Dict[int, OptimizerState] = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    @no_grad()
    def _unscale(self, optimizer):
        if not self._enable:
            return
        if self._opt_states.get(id(optimizer)) == OptimizerState.UNSCALED:
            raise RuntimeError("unscale_() has already been called on this "
                               "optimizer since the last update().")
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            g32 = p.grad._value.astype(jnp.float32) * inv
            finite = bool(jnp.isfinite(g32).all())
            if not finite:
                found = True
            p.grad._inplace_assign(g32.astype(p.grad.dtype))
        self._found_inf = found
        self._opt_states[id(optimizer)] = OptimizerState.UNSCALED

    def unscale_(self, optimizer):
        return self._unscale(optimizer)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._opt_states.get(id(optimizer)) == OptimizerState.STEPPED:
            raise RuntimeError(
                "step() has already been called since the last update()")
        if self._opt_states.get(id(optimizer)) != OptimizerState.UNSCALED:
            self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._opt_states[id(optimizer)] = OptimizerState.STEPPED

    def update(self):
        if not self._enable or not self._dynamic:
            self._opt_states.clear()
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._opt_states.clear()

    def minimize(self, optimizer, loss):
        self.step(optimizer)
        self.update()

    # scale accessors (reference parity)
    def get_scale(self):
        return self._scale

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def get_incr_ratio(self):
        return self._incr_ratio

    def set_incr_ratio(self, v):
        self._incr_ratio = v

    def get_decr_ratio(self):
        return self._decr_ratio

    def set_decr_ratio(self, v):
        self._decr_ratio = v

    def get_incr_every_n_steps(self):
        return self._incr_every_n_steps

    def set_incr_every_n_steps(self, v):
        self._incr_every_n_steps = v

    def get_decr_every_n_nan_or_inf(self):
        return self._decr_every_n

    def set_decr_every_n_nan_or_inf(self, v):
        self._decr_every_n = v

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("incr_count", 0)
        self._bad_steps = sd.get("decr_count", 0)


class GradScaler(AmpScaler):
    """reference: amp/grad_scaler.py:657."""
    pass
