"""Numeric debugging (reference: python/paddle/amp/debugging.py —
TensorCheckerConfig:173, check_numerics:361, op-stats :481).

TPU-native: instead of per-kernel nan/inf CUDA checks, a debug-mode hook on
the op dispatch layer inspects every op output (eager) — jit-compiled paths
use jax.debug/checkify when enabled.
"""
from __future__ import annotations

import enum
from typing import Optional

import numpy as np
import jax.numpy as jnp

from .._core.flags import flag_value, set_flags
from .._core.tensor import Tensor


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3


class TensorCheckerConfig:
    """reference: amp/debugging.py:173."""

    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step

    def update_and_check_step_id(self):
        return self.enable


_checker: Optional[TensorCheckerConfig] = None


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    global _checker
    _checker = checker_config
    set_flags({"check_nan_inf": checker_config.enable})


def disable_tensor_checker():
    global _checker
    _checker = None
    set_flags({"check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """reference: amp/debugging.py:361 — returns (num_nan, num_inf, num_zero)
    and raises under abort mode."""
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if not jnp.issubdtype(jnp.result_type(v), jnp.floating):
        z = Tensor(np.asarray(0, np.int32))
        return z, z, z
    n_nan = int(jnp.isnan(v).sum())
    n_inf = int(jnp.isinf(v).sum())
    n_zero = int((v == 0).sum())
    mode = debug_mode or (_checker.debug_mode if _checker else
                          DebugMode.CHECK_NAN_INF)
    if (n_nan or n_inf) and mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(
            f"[check_numerics] op={op_type} var={var_name}: "
            f"{n_nan} nan, {n_inf} inf detected")
    return (Tensor(np.asarray(n_nan, np.int32)),
            Tensor(np.asarray(n_inf, np.int32)),
            Tensor(np.asarray(n_zero, np.int32)))


_op_stats = {}


def collect_operator_stats():
    """reference: amp/debugging.py:481 — context collecting per-dtype op
    counts from the dispatch layer."""
    class _Ctx:
        def __enter__(self):
            _op_stats.clear()
            from .._core import autograd as ag
            self._prev = ag._amp_hook[0]

            def hook(name, raw):
                for v in raw:
                    if hasattr(v, "dtype"):
                        key = (name, str(jnp.result_type(v)))
                        _op_stats[key] = _op_stats.get(key, 0) + 1
                        break
                return self._prev(name, raw) if self._prev else raw
            ag.set_amp_hook(hook)
            return self

        def __exit__(self, *exc):
            from .._core import autograd as ag
            ag.set_amp_hook(self._prev)
            fp16 = {k: v for k, v in _op_stats.items() if "16" in k[1]}
            fp32 = {k: v for k, v in _op_stats.items() if "32" in k[1]}
            print("<------------------- op list of all dtypes ------------->")
            for (op, dt), c in sorted(_op_stats.items()):
                print(f"  {op:30s} {dt:10s} calls={c}")
            print(f"fp16/bf16 ops: {sum(fp16.values())}, "
                  f"fp32 ops: {sum(fp32.values())}")
            return False
    return _Ctx()


def accuracy_check(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    """reference: accuracy_check op (phi/kernels/accuracy_check_kernel) —
    elementwise closeness verdict between a result and its baseline.
    Raises with the max error on mismatch; returns True otherwise."""
    import numpy as np
    from .._core.tensor import Tensor
    xa = np.asarray(x._value if isinstance(x, Tensor) else x,
                    dtype=np.float64)
    ya = np.asarray(y._value if isinstance(y, Tensor) else y,
                    dtype=np.float64)
    ok = np.allclose(xa, ya, rtol=rtol, atol=atol, equal_nan=equal_nan)
    if not ok:
        diff = np.abs(xa - ya)
        raise AssertionError(
            f"accuracy_check failed ({name or 'tensor'}): max abs diff "
            f"{diff.max():.3e} at flat index {int(diff.argmax())} "
            f"(rtol={rtol}, atol={atol})")
    return True


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """reference: amp/debugging.py compare_accuracy — walk two directories
    of .npy tensor dumps (e.g. an fp32 run vs an amp run), compare arrays
    by filename, and write a CSV report of per-tensor max abs/rel error.
    (The reference writes xlsx from its own dump format; the TPU-native
    dump format is plain .npy per tensor.)"""
    import csv
    import os
    import numpy as np
    rows = []
    names = sorted(set(os.listdir(dump_path)) &
                   set(os.listdir(another_dump_path)))
    for fname in names:
        if not fname.endswith(".npy"):
            continue
        a = np.load(os.path.join(dump_path, fname)).astype(np.float64)
        b = np.load(os.path.join(another_dump_path, fname)).astype(
            np.float64) * float(loss_scale)
        if a.shape != b.shape:
            rows.append([fname, "SHAPE MISMATCH", str(a.shape),
                         str(b.shape)])
            continue
        diff = np.abs(a - b)
        denom = np.maximum(np.abs(a), 1e-12)
        rows.append([fname, f"{diff.max():.6e}",
                     f"{(diff / denom).max():.6e}",
                     "ok" if np.allclose(a, b, rtol=1e-4, atol=1e-6)
                     else "DIFF"])
    with open(output_filename, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["tensor", "max_abs_err", "max_rel_err", "verdict"])
        w.writerows(rows)
    return rows
