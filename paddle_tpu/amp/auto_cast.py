"""auto_cast / decorate (reference: python/paddle/amp/auto_cast.py).

O1: ops on the white list run in low precision — implemented as a thread-local
policy consulted by the op layer's matmul/conv entry points (the reference
swaps kernels per op via AmpAutoCasts; here the cast happens at trace level
and XLA fuses the converts).
O2: decorate() casts parameters themselves to low precision (pure fp16/bf16)
with optional master weights kept by the optimizer.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp

from .._core import dtype as dtypes
from .._core.tensor import Tensor

_state = threading.local()

# reference: python/paddle/amp/amp_lists.py — white = matmul/conv-like
white_list = {"matmul", "mm", "bmm", "mv", "einsum", "linear", "conv1d",
              "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
              "conv3d_transpose", "addmm", "dot_general"}
# black = numerically sensitive: stay fp32
black_list = {"exp", "log", "log2", "log10", "log1p", "softmax",
              "log_softmax", "cross_entropy", "mean", "sum", "norm",
              "logsumexp", "cumsum", "layer_norm", "batch_norm", "group_norm",
              "rms_norm", "softmax_with_cross_entropy"}


def is_auto_cast_enabled() -> bool:
    return getattr(_state, "enabled", False)


def get_amp_dtype():
    return getattr(_state, "dtype", dtypes.float16)


def get_amp_level():
    return getattr(_state, "level", "O0")


def amp_white_op(name: str) -> bool:
    st = getattr(_state, "lists", None)
    wl = st[0] if st else white_list
    return name in wl


def amp_black_op(name: str) -> bool:
    st = getattr(_state, "lists", None)
    bl = st[1] if st else black_list
    return name in bl


def maybe_autocast_inputs(name, raw_values):
    """Called by the op layer: cast float inputs of white-list ops to the amp
    dtype under O1/O2 autocast."""
    if not is_auto_cast_enabled():
        return raw_values
    if amp_black_op(name):
        tgt = jnp.float32
    elif amp_white_op(name):
        tgt = get_amp_dtype()
    else:
        return raw_values
    out = []
    for v in raw_values:
        if hasattr(v, "dtype") and jnp.issubdtype(
                jnp.result_type(v), jnp.floating):
            out.append(v.astype(tgt))
        else:
            out.append(v)
    return out


class auto_cast:
    """reference: amp/auto_cast.py:1029."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="float16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = dtypes.convert_dtype(dtype)
        wl = set(white_list)
        bl = set(black_list)
        if custom_white_list:
            wl |= set(custom_white_list)
            bl -= set(custom_white_list)
        if custom_black_list:
            bl |= set(custom_black_list)
            wl -= set(custom_black_list)
        self.lists = (wl, bl)

    def __enter__(self):
        self._saved = (getattr(_state, "enabled", False),
                       getattr(_state, "dtype", dtypes.float16),
                       getattr(_state, "level", "O0"),
                       getattr(_state, "lists", None))
        _state.enabled = self.enable
        _state.dtype = self.dtype
        _state.level = self.level if self.enable else "O0"
        _state.lists = self.lists
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level,
         _state.lists) = self._saved
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """reference: amp/auto_cast.py:1114 — O2 casts model params to amp dtype
    (norm layers kept fp32 as the reference does for BN/LN)."""
    d = dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        from ..nn.layer import norm as norm_layers
        skip_types = (norm_layers._BatchNormBase, norm_layers.LayerNorm,
                      norm_layers.GroupNorm, norm_layers.InstanceNorm1D)
        excluded = set()
        if excluded_layers:
            exl = excluded_layers if isinstance(excluded_layers,
                                                (list, tuple)) \
                else [excluded_layers]
            for e in exl:
                if isinstance(e, type):
                    skip_types = skip_types + (e,)
                else:
                    excluded.add(id(e))
        for m in model_list:
            for sub in m.sublayers(include_self=True):
                if isinstance(sub, skip_types) or id(sub) in excluded:
                    continue
                for p in sub._parameters.values():
                    if p is not None and dtypes.is_floating_point(p.dtype):
                        if getattr(p, "_master", None) is None:
                            p._master = Tensor(
                                p._value.astype(jnp.float32),
                                _internal=True)
                        p._inplace_assign(p._value.astype(d))
    if optimizers is None:
        return models
    return models, optimizers


amp_decorate = decorate

# install the autocast hook into the op dispatch layer
from .._core.autograd import set_amp_hook  # noqa: E402

set_amp_hook(maybe_autocast_inputs)
