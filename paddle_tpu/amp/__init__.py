"""AMP: auto mixed precision (reference: python/paddle/amp/ —
auto_cast.py:462 amp_guard, :1029 auto_cast, :1114 decorate;
grad_scaler.py:62 AmpScaler, :657 GradScaler).

On TPU the native mixed-precision story is bf16 (no loss scaling needed);
fp16+GradScaler is kept for API parity and works identically.
"""
from .auto_cast import (  # noqa: F401
    auto_cast, amp_guard, decorate, amp_decorate, is_auto_cast_enabled,
    get_amp_dtype, white_list, black_list,
)
from .grad_scaler import GradScaler, AmpScaler, OptimizerState  # noqa: F401
from . import debugging  # noqa: F401


def is_float16_supported(device=None):
    """reference: amp/auto_cast.py is_float16_supported — TPUs compute in
    bf16 natively; fp16 storage is supported but not MXU-preferred."""
    return True


def is_bfloat16_supported(device=None):
    """reference: amp/auto_cast.py is_bfloat16_supported — bf16 is the
    native TPU matmul dtype."""
    return True
