"""Sparse tensor containers (reference: paddle/phi/core/sparse_coo_tensor.h
:30 SparseCooTensor, sparse_csr_tensor.h SparseCsrTensor; python creation
python/paddle/sparse/creation.py)."""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from ..ops._registry import as_tensor


class SparseCooTensor:
    """indices: (ndim, nnz) int; values: (nnz, *dense_dims)."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices = indices if isinstance(indices, jax.Array) \
            else jnp.asarray(np.asarray(indices), jnp.int32)
        self.values = values._value if isinstance(values, Tensor) \
            else jnp.asarray(values)
        self.shape = list(shape)
        self._coalesced = coalesced

    @property
    def dtype(self):
        return np.dtype(jnp.result_type(self.values))

    @property
    def nnz(self):
        return self.indices.shape[1]

    def to_dense(self) -> Tensor:
        out = jnp.zeros(tuple(self.shape), self.values.dtype)
        idx = tuple(self.indices[i] for i in range(self.indices.shape[0]))
        if self.values.dtype == jnp.bool_:
            # scatter-add has no bool rule; bools scatter by set (a
            # coalesced bool pattern has no duplicates to sum anyway)
            return Tensor(out.at[idx].set(self.values), _internal=True)
        return Tensor(out.at[idx].add(self.values), _internal=True)

    def coalesce(self) -> "SparseCooTensor":
        """Merge duplicate coordinates (sums values)."""
        nd = self.indices.shape[0]
        strides = np.cumprod([1] + self.shape[:0:-1])[::-1]
        flat = sum(self.indices[i] * int(strides[i]) for i in range(nd))
        order = jnp.argsort(flat)
        flat_s = flat[order]
        vals_s = self.values[order]
        uniq, inv = jnp.unique(flat_s, return_inverse=True,
                               size=self.nnz, fill_value=-1)
        summed = jax.ops.segment_sum(vals_s, inv, num_segments=self.nnz)
        new_idx = []
        rem = uniq
        for s in strides:
            new_idx.append((rem // int(s)).astype(jnp.int32))
            rem = rem % int(s)
        return SparseCooTensor(jnp.stack(new_idx), summed, self.shape,
                               coalesced=True)

    def numpy(self):
        return np.asarray(self.to_dense()._value)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype.name})")


class SparseCsrTensor:
    """crows: (nrows+1,), cols: (nnz,), values: (nnz,) — 2D only (the
    reference supports batched 3D; batch = leading dim loop here)."""

    def __init__(self, crows, cols, values, shape):
        self.crows = jnp.asarray(np.asarray(crows), jnp.int32)
        self.cols = jnp.asarray(np.asarray(cols), jnp.int32)
        self.values = values._value if isinstance(values, Tensor) \
            else jnp.asarray(values)
        self.shape = list(shape)

    @property
    def dtype(self):
        return np.dtype(jnp.result_type(self.values))

    @property
    def nnz(self):
        return self.cols.shape[0]

    def _row_indices(self):
        counts = self.crows[1:] - self.crows[:-1]
        return jnp.repeat(jnp.arange(self.shape[0]), counts,
                          total_repeat_length=self.nnz)

    def to_dense(self) -> Tensor:
        rows = self._row_indices()
        out = jnp.zeros(tuple(self.shape), self.values.dtype)
        return Tensor(out.at[rows, self.cols].add(self.values),
                      _internal=True)

    def to_coo(self) -> SparseCooTensor:
        return SparseCooTensor(jnp.stack([self._row_indices(), self.cols]),
                               self.values, self.shape)

    def numpy(self):
        return np.asarray(self.to_dense()._value)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype.name})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor."""
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = np.asarray(values if not isinstance(values, Tensor)
                      else values.numpy())
    if dtype is not None:
        from .._core import dtype as dtypes
        vals = vals.astype(dtypes.convert_dtype(dtype))
    elif vals.dtype == np.float64:
        vals = vals.astype(np.float32)
    if shape is None:
        shape = list(idx.max(axis=1) + 1)
    return SparseCooTensor(jnp.asarray(idx, jnp.int32), jnp.asarray(vals),
                           list(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = np.asarray(values if not isinstance(values, Tensor)
                      else values.numpy())
    if dtype is not None:
        from .._core import dtype as dtypes
        vals = vals.astype(dtypes.convert_dtype(dtype))
    elif vals.dtype == np.float64:
        vals = vals.astype(np.float32)
    return SparseCsrTensor(crows, cols, vals, list(shape))


def to_sparse_coo(x, sparse_dim: Optional[int] = None) -> SparseCooTensor:
    """Dense Tensor -> COO (reference: Tensor.to_sparse_coo)."""
    if isinstance(x, SparseCsrTensor):
        return x.to_coo()
    x = as_tensor(x)
    arr = np.asarray(x._value)
    nd = sparse_dim or arr.ndim
    idx = np.stack(np.nonzero(arr)[:nd])
    vals = arr[tuple(idx)]
    return SparseCooTensor(jnp.asarray(idx, jnp.int32), jnp.asarray(vals),
                           list(arr.shape))


def to_sparse_csr(x) -> SparseCsrTensor:
    if isinstance(x, SparseCooTensor):
        x = x.to_dense()
    x = as_tensor(x)
    arr = np.asarray(x._value)
    assert arr.ndim == 2
    rows, cols = np.nonzero(arr)
    vals = arr[rows, cols]
    crows = np.zeros(arr.shape[0] + 1, np.int32)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols, vals, list(arr.shape))


def to_dense(x):
    return x.to_dense()


def is_sparse_coo(x) -> bool:
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x) -> bool:
    return isinstance(x, SparseCsrTensor)
