"""Sparse nn layers (reference: python/paddle/sparse/nn/ — ReLU, Softmax,
Conv3D (submanifold), BatchNorm; kernels paddle/phi/kernels/sparse/).

TPU note: submanifold sparse conv has no XLA analog; SubmConv3D here
gathers neighbor values per active site (static nnz) — correct semantics
at research scale; a Pallas gather-kernel is the optimization path."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from .tensor import SparseCooTensor
from . import ops as sops


class ReLU(Layer):
    def forward(self, x):
        return sops.relu(x)


class Softmax(Layer):
    """Row-wise softmax over the sparsity pattern (reference:
    sparse/nn/functional/activation.py softmax, 2D CSR/COO)."""

    def __init__(self, axis=-1):
        super().__init__()

    def forward(self, x):
        coo = x.to_coo() if not isinstance(x, SparseCooTensor) else x
        rows = coo.indices[0]
        nrows = coo.shape[0]
        vmax = jax.ops.segment_max(coo.values, rows, num_segments=nrows)
        ex = jnp.exp(coo.values - vmax[rows])
        denom = jax.ops.segment_sum(ex, rows, num_segments=nrows)
        return SparseCooTensor(coo.indices, ex / denom[rows], coo.shape)


class BatchNorm(Layer):
    """BatchNorm over sparse values (reference: sparse/nn/layer/norm.py) —
    normalizes the value vectors of active sites."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, **kw):
        super().__init__()
        from ..nn.initializer.initializer import Constant
        self._eps = epsilon
        self._momentum = momentum
        self.weight = self.create_parameter(
            [num_features], default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)

    def forward(self, x):
        v = x.values
        mean = jnp.mean(v, axis=0)
        var = jnp.var(v, axis=0)
        out = (v - mean) * jax.lax.rsqrt(var + self._eps)
        out = out * self.weight._value + self.bias._value
        return SparseCooTensor(x.indices, out, x.shape)
