"""paddle.sparse parity (reference: python/paddle/sparse/ — COO/CSR tensor
creation creation.py sparse_coo_tensor/sparse_csr_tensor, unary/binary
ops, matmul, nn layers; C++ paddle/phi/core/sparse_coo_tensor.h,
sparse_csr_tensor.h, kernels paddle/phi/kernels/sparse/).

TPU-native: XLA has no sparse formats, so SparseCooTensor/SparseCsrTensor
carry (indices, values) as dense jnp arrays with STATIC nnz (TPU-friendly:
gather/scatter/segment_sum lower to vectorized ops), and compute either
stays in index space (elementwise on values, spmm via segment-sum) or
densifies when the op needs it. `is_sparse_*`, `to_dense`, `to_sparse_coo`
match the reference Tensor methods.
"""
from .tensor import (  # noqa: F401
    SparseCooTensor, SparseCsrTensor, sparse_coo_tensor, sparse_csr_tensor,
    to_dense, to_sparse_coo, to_sparse_csr, is_sparse_coo, is_sparse_csr,
)
from .ops import (  # noqa: F401
    add, subtract, multiply, divide, matmul, masked_matmul, relu, abs, sin,
    tanh, pow, neg, cast, transpose, sum, sparse_coo_tensor_values_like,
    coalesce, values, indices, divide_scalar, mask_as,
    sqrt, square, log1p, expm1, asin, atan, sinh, asinh, atanh,
    deg2rad, rad2deg, tan, isnan, is_same_shape, addmm, mv, reshape,
    slice, pca_lowrank,
)
from . import nn  # noqa: F401
