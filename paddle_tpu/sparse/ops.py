"""Sparse ops (reference: python/paddle/sparse/{unary,binary}.py, matmul
python/paddle/sparse/multiply.py etc.; kernels paddle/phi/kernels/sparse/).

Elementwise unary ops act on values (index structure preserved); binary
ops and matmul use segment-sum index arithmetic; ops that need dense
semantics densify (documented per-op)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import SparseCooTensor, SparseCsrTensor, to_sparse_coo
from .._core.tensor import Tensor
from ..ops._registry import as_tensor


def _unary(fn, zero_preserving=True):
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, fn(x.values), x.shape)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x.crows, x.cols, fn(x.values), x.shape)
        return Tensor(fn(as_tensor(x)._value), _internal=True)
    return op


relu = _unary(jax.nn.relu)
abs = _unary(jnp.abs)
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
neg = _unary(jnp.negative)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from .._core import dtype as dtypes
    vd = dtypes.convert_dtype(value_dtype) if value_dtype else None
    if isinstance(x, SparseCooTensor):
        idx = x.indices.astype(index_dtype) if index_dtype else x.indices
        return SparseCooTensor(idx, x.values.astype(vd) if vd else x.values,
                               x.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows, x.cols,
                               x.values.astype(vd) if vd else x.values,
                               x.shape)
    raise TypeError


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        idx = jnp.stack([x.indices[p] for p in perm])
        shape = [x.shape[p] for p in perm]
        return SparseCooTensor(idx, x.values, shape)
    raise TypeError("transpose supports COO")


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = x.to_dense()
    from .. import ops as dense_ops
    return dense_ops.sum(d, axis=axis, dtype=dtype, keepdim=keepdim)


def _binary(fn):
    def op(x, y, name=None):
        # same-structure fast path; else densify (reference kernels merge
        # index sets — dense round-trip is TPU-cheap at test scales)
        if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
            if x.indices.shape == y.indices.shape and \
                    bool(jnp.all(x.indices == y.indices)):
                return SparseCooTensor(x.indices, fn(x.values, y.values),
                                       x.shape)
            xd, yd = x.to_dense()._value, y.to_dense()._value
            return to_sparse_coo(Tensor(fn(xd, yd), _internal=True))
        xd = x.to_dense()._value if isinstance(
            x, (SparseCooTensor, SparseCsrTensor)) else as_tensor(x)._value
        yd = y.to_dense()._value if isinstance(
            y, (SparseCooTensor, SparseCsrTensor)) else as_tensor(y)._value
        return Tensor(fn(xd, yd), _internal=True)
    return op


add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.true_divide)


def matmul(x, y, name=None):
    """spmm: sparse @ dense via gather + segment-sum (maps to vectorized
    gather/scatter on TPU — reference: paddle/phi/kernels/sparse/matmul
    kernels use cuSPARSE)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_coo()
    if isinstance(x, SparseCooTensor):
        yv = as_tensor(y)._value if not isinstance(
            y, (SparseCooTensor, SparseCsrTensor)) else y.to_dense()._value
        assert len(x.shape) == 2 and yv.ndim == 2
        rows, cols = x.indices[0], x.indices[1]
        contrib = x.values[:, None] * yv[cols]          # (nnz, N)
        out = jax.ops.segment_sum(contrib, rows, num_segments=x.shape[0])
        return Tensor(out, _internal=True)
    # dense @ sparse -> transpose trick
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        yt = y.to_coo() if isinstance(y, SparseCsrTensor) else y
        xt = as_tensor(x)._value
        out = matmul(transpose(yt, [1, 0]), Tensor(xt.T, _internal=True))
        return Tensor(out._value.T, _internal=True)
    raise TypeError


def masked_matmul(x, y, mask, name=None):
    """dense@dense evaluated only at mask's sparsity pattern (reference:
    sparse.masked_matmul): out.values[i] = x[r_i] . y[:, c_i]."""
    xv = as_tensor(x)._value
    yv = as_tensor(y)._value
    rows, cols = mask.indices[0], mask.indices[1]
    vals = jnp.einsum("nk,nk->n", xv[rows], yv[:, cols].T)
    return SparseCooTensor(mask.indices, vals, mask.shape)


def sparse_coo_tensor_values_like(x, values):
    return SparseCooTensor(x.indices, values, x.shape)


def coalesce(x, name=None):
    """reference: sparse_ops.yaml coalesce — merge duplicate coordinates."""
    return x.coalesce() if isinstance(x, SparseCooTensor) else x


def values(x, name=None):
    """reference: sparse_ops.yaml values — the non-zero values as a dense
    Tensor."""
    return Tensor(x.values, _internal=True)


def indices(x, name=None):
    """reference: sparse_ops.yaml indices."""
    return Tensor(x.indices, _internal=True)


def divide_scalar(x, scalar, name=None):
    """reference: sparse_ops.yaml divide_scalar — zero-preserving."""
    return sparse_coo_tensor_values_like(x, x.values / scalar) \
        if isinstance(x, SparseCooTensor) else type(x)(
            x.crows, x.cols, x.values / scalar, x.shape)


def mask_as(x, mask, name=None):
    """reference: sparse_ops.yaml mask_as — take the dense tensor's values
    at the sparse mask's coordinates (paddle.sparse.mask_as)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    m = mask.to_coo() if isinstance(mask, SparseCsrTensor) else mask
    idx = tuple(m.indices[i] for i in range(m.indices.shape[0]))
    # coalesce pads empty slots with -1 coordinates; zero their values so
    # the wrap-around gather contributes nothing
    valid = (m.indices >= 0).all(axis=0)
    vals = jnp.where(
        valid.reshape((-1,) + (1,) * (xv[idx].ndim - 1)), xv[idx], 0)
    out = SparseCooTensor(m.indices, vals, m.shape)
    if isinstance(mask, SparseCsrTensor):
        from .tensor import to_sparse_csr
        return to_sparse_csr(out)
    return out


deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
tan = _unary(jnp.tan)
isnan = _unary(jnp.isnan)


def is_same_shape(x, y) -> bool:
    """reference: sparse/binary.py is_same_shape."""
    xs = x.shape if not hasattr(x, "dense_shape") else x.dense_shape
    ys = y.shape if not hasattr(y, "dense_shape") else y.dense_shape
    return tuple(xs) == tuple(ys)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """reference: sparse/multiary.py addmm — beta*input + alpha*(x@y),
    sparse x with dense input/y -> dense."""
    return input * beta + matmul(x, y) * alpha


def mv(x, vec, name=None):
    """reference: sparse/matmul.py mv — sparse matrix x dense vector."""
    from ..ops.manipulation import squeeze, unsqueeze
    return squeeze(matmul(x, unsqueeze(vec, -1)), -1)


def reshape(x, shape, name=None):
    """reference: sparse/unary.py reshape — COO/CSR reshape via the dense
    layout (host-sized sparse tensors; the TPU compute path densifies
    anyway)."""
    from .tensor import to_sparse_coo, to_sparse_csr, is_sparse_csr
    from ..ops.manipulation import reshape as dense_reshape
    d = dense_reshape(x.to_dense(), shape)
    if is_sparse_csr(x):
        return to_sparse_csr(d)
    return to_sparse_coo(d, len(d.shape))


_py_slice = slice  # captured before the op below shadows the builtin


def slice(x, axes, starts, ends, name=None):
    """reference: sparse/unary.py slice — via the dense layout."""
    from .tensor import to_sparse_coo, to_sparse_csr, is_sparse_csr
    d = x.to_dense()
    slicer = [_py_slice(None)] * len(d.shape)
    for ax, st, en in zip(axes, starts, ends):
        slicer[ax] = _py_slice(st, en)
    out = d[tuple(slicer)]
    if is_sparse_csr(x):
        return to_sparse_csr(out)
    return to_sparse_coo(out, len(out.shape))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference: sparse/pca_lowrank (tensor/linalg pca_lowrank) —
    randomized PCA: returns (U, S, V) with x ~ U diag(S) V^T."""
    from .._core.autograd import apply as _apply
    from ..ops._registry import as_tensor as _at
    from .tensor import SparseCooTensor, SparseCsrTensor
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        x = x.to_dense()
    x = _at(x)
    m, n = x.shape[-2], x.shape[-1]
    if q is None:
        q = min(6, m, n)
    import numpy as _np
    g = _np.random.RandomState(0).randn(n, q).astype(_np.float32)

    def f(v):
        a = v.astype(jnp.float32)
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        y = a @ g
        for _ in range(niter):
            y = a @ (a.T @ y)
        qm, _ = jnp.linalg.qr(y)
        b = qm.T @ a
        u, s, vt = jnp.linalg.svd(b, full_matrices=False)
        return qm @ u, s, vt.T
    return _apply(f, x, name="pca_lowrank", multi_out=True)
