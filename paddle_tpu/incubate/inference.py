"""paddle.incubate.inference parity (reference: python/paddle/incubate/
inference/__init__.py — the ``wrap_decorator`` d2s inference accelerator).

On TPU the capability is jit compilation itself: ``@paddle.incubate.
inference.wrap_inference`` compiles the wrapped callable with the same
trace-and-cache machinery as ``paddle.jit.to_static``.
"""
from __future__ import annotations


def wrap_inference(fn=None, **kwargs):
    """Compile a callable for inference (reference: incubate/inference
    wrap_decorator). Accepts and ignores the CUDA-specific tuning kwargs
    (cache_static_model etc.) — XLA compilation cache subsumes them."""
    from ..jit import to_static

    def deco(f):
        return to_static(f)

    return deco(fn) if fn is not None else deco
