"""Misc incubate operators (reference: python/paddle/incubate/operators/)."""
from __future__ import annotations

import jax.numpy as jnp

from .._core.autograd import apply
from ..ops._registry import as_tensor


def identity_loss(x, reduction="none"):
    """reference: incubate/operators/__init__.py identity_loss (kernel
    phi identity_loss) — marks a tensor as a loss and reduces it;
    reduction: 0/'sum', 1/'mean', 2/'none'."""
    names = {0: "sum", 1: "mean", 2: "none"}
    if isinstance(reduction, int):
        reduction = names.get(reduction, reduction)
    if reduction not in ("sum", "mean", "none"):
        raise ValueError(f"unknown reduction {reduction!r}")

    def f(v):
        if reduction == "sum":
            return jnp.sum(v)
        if reduction == "mean":
            return jnp.mean(v)
        return v

    return apply(f, as_tensor(x), name="identity_loss")
