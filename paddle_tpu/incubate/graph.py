"""Graph-learning sampling utilities (reference: python/paddle/incubate/
operators/graph_{send_recv,reindex,sample_neighbors,khop_sampler}.py).

TPU-native split: message passing (``graph_send_recv``) is the jit-able
``geometric`` segment path; the SAMPLERS are host-side data-preparation
ops (inherently dynamic-shaped — the reference runs them in C++ on CPU
or with GPU hashtables), so they run in numpy on the host, like the
DataLoader they feed.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .._core.tensor import Tensor
from ..ops._registry import as_tensor


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """reference: incubate/operators/graph_send_recv.py — renamed
    ``geometric.send_u_recv`` (pool_type -> reduce_op)."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size, name=name)


def _np(t):
    if isinstance(t, Tensor):
        return np.asarray(t._value)
    return np.asarray(t)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None, seed=None):
    """reference: incubate/operators/graph_sample_neighbors.py — for each
    input node, sample up to ``sample_size`` neighbors from the CSC graph
    (row = concatenated neighbor lists, colptr = per-node offsets).
    Returns (out_neighbors, out_count[, out_eids])."""
    rown = _np(row)
    cp = _np(colptr)
    nodes = _np(input_nodes).reshape(-1)
    eidsn = _np(eids) if eids is not None else None
    # deterministic under paddle.seed: derive the host-side seed from
    # the framework's PRNG stream (a per-call explicit seed wins)
    if seed is None:
        from .._core import random as _random
        import jax as _jax
        seed = int(np.asarray(
            _jax.random.bits(_random.next_rng_key(), dtype=np.uint32)))
    rng = np.random.default_rng(seed)
    neigh_parts, eid_parts, counts = [], [], []
    for n in nodes:
        lo, hi = int(cp[n]), int(cp[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        neigh_parts.append(rown[sel])
        if eidsn is not None:
            eid_parts.append(eidsn[sel])
        counts.append(len(sel))
    out_n = np.concatenate(neigh_parts) if neigh_parts else \
        np.zeros((0,), rown.dtype)
    out_c = np.asarray(counts, np.int32)
    outs = (Tensor(out_n), Tensor(out_c))
    if return_eids:
        if eidsn is None:
            raise ValueError("return_eids=True requires eids")
        out_e = np.concatenate(eid_parts) if eid_parts else \
            np.zeros((0,), eidsn.dtype)
        outs = outs + (Tensor(out_e),)
    return outs


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """reference: incubate/operators/graph_reindex.py — contiguous ids
    from 0 with the input nodes first (multi-edge-type supported: count
    length = k * len(x) blocks). Returns (reindex_src, reindex_dst,
    out_nodes)."""
    if flag_buffer_hashtable and (value_buffer is None
                                  or index_buffer is None):
        raise ValueError("`value_buffer` and `index_buffer` should not "
                         "be None if `flag_buffer_hashtable` is True.")
    xs = _np(x).reshape(-1)
    nb = _np(neighbors).reshape(-1)
    ct = _np(count).reshape(-1)
    if len(ct) % len(xs) != 0:
        raise ValueError(
            f"count length {len(ct)} must be a multiple of len(x) "
            f"{len(xs)}")
    idmap = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    src = np.empty(len(nb), np.int64)
    for i, v in enumerate(nb):
        v = int(v)
        j = idmap.get(v)
        if j is None:
            j = len(out_nodes)
            idmap[v] = j
            out_nodes.append(v)
        src[i] = j
    dst = np.repeat(np.tile(np.arange(len(xs), dtype=np.int64),
                            len(ct) // len(xs)), ct)
    return (Tensor(src.astype(xs.dtype)), Tensor(dst.astype(xs.dtype)),
            Tensor(np.asarray(out_nodes, xs.dtype)))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes: Sequence[int],
                       sorted_eids=None, return_eids=False, name=None):
    """reference: incubate/operators/graph_khop_sampler.py — multi-hop
    neighbor sampling + reindex. Returns (edge_src, edge_dst,
    sample_index, reindex_nodes[, edge_eids])."""
    nodes = _np(input_nodes).reshape(-1)
    frontier = nodes
    all_neigh, all_count, all_eids = [], [], []
    for sz in sample_sizes:
        res = graph_sample_neighbors(
            row, colptr, Tensor(frontier), eids=sorted_eids,
            sample_size=sz, return_eids=return_eids)
        nb, ct = _np(res[0]), _np(res[1])
        all_neigh.append(nb)
        all_count.append((frontier, ct))
        if return_eids:
            all_eids.append(_np(res[2]))
        # next frontier: newly seen nodes
        frontier = np.unique(nb)
    # unique sample universe, input nodes first
    seen = {int(v): i for i, v in enumerate(nodes)}
    universe = list(nodes)
    for nb in all_neigh:
        for v in nb:
            v = int(v)
            if v not in seen:
                seen[v] = len(universe)
                universe.append(v)
    srcs, dsts = [], []
    for (front, ct), nb in zip(all_count, all_neigh):
        dst = np.repeat(front, ct)
        srcs.append(np.asarray([seen[int(v)] for v in nb], np.int64))
        dsts.append(np.asarray([seen[int(v)] for v in dst], np.int64))
    edge_src = np.concatenate(srcs) if srcs else np.zeros((0,), np.int64)
    edge_dst = np.concatenate(dsts) if dsts else np.zeros((0,), np.int64)
    sample_index = np.asarray(universe, nodes.dtype)
    reindex_nodes = np.asarray([seen[int(v)] for v in nodes], np.int64)
    outs = (Tensor(edge_src.reshape(-1, 1)), Tensor(edge_dst.reshape(-1, 1)),
            Tensor(sample_index), Tensor(reindex_nodes))
    if return_eids:
        eids_all = np.concatenate(all_eids) if all_eids else \
            np.zeros((0,), np.int64)
        outs = outs + (Tensor(eids_all),)
    return outs
