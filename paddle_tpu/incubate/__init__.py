"""paddle.incubate parity (reference: python/paddle/incubate/ — 42.4k LoC:
fused-op functional APIs, MoE models, DistributedFusedLamb, ASP, autotune).

On TPU the "fused" ops are expressed as jnp compositions XLA fuses (plus
Pallas kernels for attention); the API surface is kept for drop-in parity.
"""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
