"""paddle.incubate parity (reference: python/paddle/incubate/ — 42.4k LoC:
fused-op functional APIs, MoE models, DistributedFusedLamb, ASP, autotune).

On TPU the "fused" ops are expressed as jnp compositions XLA fuses (plus
Pallas kernels for attention); the API surface is kept for drop-in parity.
"""
from . import nn  # noqa: F401
from . import layers  # noqa: F401
from . import distributed  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from . import inference  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
# reference exposes the segment reductions at incubate top level
# (python/paddle/incubate/__init__.py)
from ..geometric import (segment_sum, segment_mean, segment_max,  # noqa: F401
                         segment_min)
from .nn.functional import (softmax_mask_fuse,  # noqa: F401
                            softmax_mask_fuse_upper_triangle)
from .graph import (graph_send_recv, graph_khop_sampler,  # noqa: F401
                    graph_reindex, graph_sample_neighbors)
from .ops import identity_loss  # noqa: F401
