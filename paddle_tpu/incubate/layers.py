"""Rec-sys / legacy incubate layers (reference: python/paddle/incubate/
layers/nn.py — shuffle_batch:274, partial_concat:346, partial_sum:426,
tdm_child:488, tdm_sampler:583, rank_attention:863, batch_fc:932,
correlation:1003) plus kernel-only legacy ops the reference snapshot keeps
registered but no longer wraps in Python (affine_channel, add_position_
encoding, bipartite_match, box_clip, ctc_align, chunk_eval, im2sequence —
paddle/phi/kernels/cpu/*.cc).

TPU-native re-design notes:
- LoD inputs become padded batches + explicit ``lengths`` (dynamic row
  counts defeat XLA static shapes); batch-dims stay leading.
- Parameter-creating reference APIs (``param_attr`` + LayerHelper) become
  functional: weights are passed in as tensors, matching this framework's
  functional substrate (create them with ``paddle.create_parameter``).
- Sampling ops (tdm_sampler) are host-side numpy like the other data-prep
  samplers (incubate/graph.py); gather/compute ops are jnp and jit-able.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .._core.autograd import apply
from .._core.tensor import Tensor
from ..ops._registry import as_tensor

__all__ = [
    "shuffle_batch", "partial_concat", "partial_sum", "tdm_child",
    "tdm_sampler", "rank_attention", "batch_fc", "correlation",
    "affine_channel", "add_position_encoding", "bipartite_match",
    "box_clip", "ctc_align", "chunk_eval", "im2sequence",
    "detection_map", "attention_lstm", "match_matrix_tensor",
]


def _np(t):
    if isinstance(t, Tensor):
        return np.asarray(t._value)
    return np.asarray(t)


# --------------------------------------------------------------- shuffle
def shuffle_batch(x, seed=None, startup_seed: int = 0, name=None):
    """Randomly permute the batch rows (all dims but the last are the
    "batch"; rows of width ``x.shape[-1]`` move as units).

    reference: incubate/layers/nn.py:274 + cpu/shuffle_batch_kernel.cc
    (the reference kernel draws fresh entropy from std::random_device even
    when seeded; here the permutation derives from ``seed`` /
    ``startup_seed`` / the framework PRNG stream, so runs under
    ``paddle.seed`` are reproducible — deviation documented in
    MIGRATION.md). Differentiable: the backward scatters grads through the
    inverse permutation (reference shuffle_batch_grad).
    """
    t = as_tensor(x)
    if seed is None:
        seed = startup_seed
        if seed == 0:
            from .._core import random as _random
            seed = int(np.asarray(
                jax.random.bits(_random.next_rng_key(), dtype=np.uint32)))
    elif isinstance(seed, Tensor):
        seed = int(np.asarray(seed._value).reshape(-1)[0])
    n = 1
    for d in t.shape[:-1]:
        n *= int(d)
    perm = jnp.asarray(np.random.default_rng(seed).permutation(n))

    def fn(v):
        flat = v.reshape((n,) + v.shape[len(v.shape) - 1:])
        return jnp.take(flat, perm, axis=0).reshape(v.shape)

    return apply(fn, t, name="shuffle_batch")


# ------------------------------------------------------- partial concat/sum
def _partial_slice_bounds(in_size: int, start_index: int, length: int):
    start = start_index if start_index >= 0 else in_size + start_index
    if not 0 <= start < in_size:
        raise ValueError(
            f"partial start_index {start_index} out of range for width "
            f"{in_size}")
    plen = length if length >= 0 else in_size - start
    if start + plen > in_size:
        raise ValueError("partial slice exceeds input width")
    return start, plen


def partial_concat(x, start_index: int = 0, length: int = -1, name=None):
    """Concat the column slice ``[start_index, start_index+length)`` of
    every 2-D input along axis 1.

    reference: incubate/layers/nn.py:346 +
    impl/partial_concat_kernel_impl.h (negative start counts from the
    right; length -1 means "to the end").
    """
    ts = [as_tensor(t) for t in x]
    if ts[0].ndim != 2:
        raise ValueError("partial_concat expects 2-D inputs")
    if any(tuple(t.shape) != tuple(ts[0].shape) for t in ts[1:]):
        raise ValueError("partial_concat inputs must share one shape "
                         f"(got {[tuple(t.shape) for t in ts]})")
    start, plen = _partial_slice_bounds(int(ts[0].shape[1]),
                                        start_index, length)

    def fn(*vs):
        return jnp.concatenate([v[:, start:start + plen] for v in vs],
                               axis=1)

    return apply(fn, *ts, name="partial_concat")


def partial_sum(x, start_index: int = 0, length: int = -1, name=None):
    """Sum the column slice ``[start_index, start_index+length)`` across
    the 2-D inputs. reference: incubate/layers/nn.py:426 +
    impl/partial_sum_kernel_impl.h."""
    ts = [as_tensor(t) for t in x]
    if ts[0].ndim != 2:
        raise ValueError("partial_sum expects 2-D inputs")
    if any(tuple(t.shape) != tuple(ts[0].shape) for t in ts[1:]):
        raise ValueError("partial_sum inputs must share one shape "
                         f"(got {[tuple(t.shape) for t in ts]})")
    start, plen = _partial_slice_bounds(int(ts[0].shape[1]),
                                        start_index, length)

    def fn(*vs):
        acc = vs[0][:, start:start + plen]
        for v in vs[1:]:
            acc = acc + v[:, start:start + plen]
        return acc

    return apply(fn, *ts, name="partial_sum")


# ------------------------------------------------------------------- TDM
def tdm_child(x, tree_info, child_nums: int, dtype="int32", name=None):
    """Children lookup in a TDM tree. ``tree_info`` rows are
    ``[item_id, layer_id, ancestor_id, child_0..child_{n-1}]``; node 0 is
    the padding node. Returns ``(child, leaf_mask)`` of shape
    ``x.shape + (child_nums,)``; nodes without children emit zeros with
    mask 0, a child's mask is 1 iff its item_id != 0 (leaf).

    reference: incubate/layers/nn.py:488 + cpu/tdm_child_kernel.cc
    (TDMChildInner).
    """
    xt = as_tensor(x)
    info = as_tensor(tree_info)
    odt = jnp.int64 if str(dtype) in ("int64", "paddle.int64") else jnp.int32

    def fn(ids, ti):
        ids = ids.astype(jnp.int32)
        has_child = (ids != 0) & (ti[ids, 3] != 0)
        child = ti[ids[..., None], 3 + jnp.arange(child_nums)]
        child = jnp.where(has_child[..., None], child, 0)
        leaf = jnp.where(has_child[..., None], (ti[child, 0] != 0), False)
        return child.astype(odt), leaf.astype(odt)

    return apply(fn, xt, info, name="tdm_child", multi_out=True)


def tdm_sampler(x, travel, layer, neg_samples_num_list: Sequence[int],
                layer_offset_lod: Sequence[int], output_positive: bool = True,
                output_list: bool = False, seed: int = 0,
                dtype="int32", name=None):
    """Layer-wise negative sampling over a TDM tree.

    For each input leaf id ``i`` and tree layer ``l``: the positive node
    is ``travel[i, l]`` (0 = padding -> zeros with mask 0), plus
    ``neg_samples_num_list[l]`` negatives drawn uniformly without
    replacement from that layer's nodes (``layer`` flat array sliced by
    ``layer_offset_lod``), never equal to the positive. Returns
    ``(out, label, mask)`` each ``(N, sum(neg + output_positive))``, or
    per-layer splits when ``output_list``.

    reference: incubate/layers/nn.py:583 + cpu/tdm_sampler_kernel.cc
    (TDMSamplerInner). Host-side numpy (sampling is data prep, like
    incubate/graph.py samplers).
    """
    ids = _np(x).reshape(-1).astype(np.int64)
    trav = _np(travel)
    lay = _np(layer).reshape(-1)
    offs = list(layer_offset_lod)
    layer_nums = len(neg_samples_num_list)
    if trav.ndim == 1:
        trav = trav.reshape(-1, layer_nums)
    widths = [n + int(output_positive) for n in neg_samples_num_list]
    res_len = sum(widths)
    n_ids = len(ids)
    odt = np.int64 if str(dtype) in ("int64", "paddle.int64") else np.int32
    out = np.zeros((n_ids, res_len), odt)
    label = np.zeros((n_ids, res_len), odt)
    mask = np.ones((n_ids, res_len), odt)
    rng = np.random.default_rng(seed if seed else None)
    for i, leaf in enumerate(ids):
        off = 0
        for l_idx in range(layer_nums):
            k = neg_samples_num_list[l_idx]
            node_lo, node_hi = offs[l_idx], offs[l_idx + 1]
            node_nums = node_hi - node_lo
            if k > node_nums - 1:
                raise ValueError(
                    f"neg_samples_num_list[{l_idx}]={k} must be <= layer "
                    f"node count - 1 ({node_nums - 1})")
            pos = int(trav[leaf, l_idx])
            w = widths[l_idx]
            if pos == 0:  # padding layer for this leaf
                out[i, off:off + w] = 0
                label[i, off:off + w] = 0
                mask[i, off:off + w] = 0
                off += w
                continue
            if output_positive:
                out[i, off] = pos
                label[i, off] = 1
                off += 1
            layer_nodes = lay[node_lo:node_hi]
            cand = np.flatnonzero(layer_nodes != pos)
            sel = rng.choice(len(cand), size=k, replace=False)
            out[i, off:off + k] = layer_nodes[cand[sel]]
            label[i, off:off + k] = 0
            off += k
    outs = (Tensor(out), Tensor(label), Tensor(mask))
    if output_list:
        splits = np.cumsum(widths)[:-1]
        return tuple([Tensor(p) for p in np.split(_np(t), splits, axis=1)]
                     for t in outs)
    return outs


# --------------------------------------------------------- rank attention
def rank_attention(input, rank_offset, rank_param, max_rank: int = 3,
                   max_size: int = 0, name=None):
    """Rank-aware attention for rec-sys ranking.

    ``rank_offset`` rows are ``[rank_i, (rank_j_1, ins_1), ...,
    (rank_j_k, ins_k)]`` (1-based ranks, 0 = absent). For instance ``i``
    the expanded feature block k is ``input[ins_k]`` and the per-instance
    weight block is ``rank_param`` block ``(rank_i-1)*max_rank +
    (rank_j_k-1)`` of shape (D, out); output is the sum of block matmuls.

    ``rank_param`` shape: ``(D * max_rank * max_rank, out)``; ``max_size``
    is a GPU scratch-buffer hint in the reference — ignored here.

    reference: incubate/layers/nn.py:863 + funcs/rank_attention.cu.h
    (expand_input_by_rank_kernel / expand_rank_attention_param_kernel).
    Functional deviation: the weight is passed in, not created from a
    ParamAttr (MIGRATION.md).
    """
    xt, ro, pt = as_tensor(input), as_tensor(rank_offset), \
        as_tensor(rank_param)
    d = int(xt.shape[1])
    out_col = int(pt.shape[1])
    if int(pt.shape[0]) != d * max_rank * max_rank:
        raise ValueError("rank_param rows must equal D * max_rank^2")

    def fn(x, off, p):
        off = off.astype(jnp.int32)
        lower = off[:, 0] - 1                       # (N,)
        pr = p.reshape(max_rank * max_rank, d, out_col)
        acc = jnp.zeros((x.shape[0], out_col), x.dtype)
        for k in range(max_rank):
            faster = off[:, 2 * k + 1] - 1
            idx = off[:, 2 * k + 2]
            valid = (lower >= 0) & (faster >= 0)
            xk = jnp.where(valid[:, None], x[idx], 0)            # (N, D)
            blk = jnp.clip(lower * max_rank + faster, 0, None)
            wk = jnp.where(valid[:, None, None], pr[blk], 0)     # (N,D,O)
            acc = acc + jnp.einsum("nd,ndo->no", xk, wk)
        return acc

    return apply(fn, xt, ro, pt, name="rank_attention", nondiff=(1,))


def batch_fc(input, w, bias=None, act: Optional[str] = None, name=None):
    """Per-slot batched FC: ``out[s] = act(input[s] @ w[s] + bias[s])``
    with input (S, N, D), w (S, D, O), bias (S, O).

    reference: incubate/layers/nn.py:932 + cpu batch_fc kernel (slot-major
    batched gemm + bias + activation). Weight passed functionally.
    """
    xt, wt = as_tensor(input), as_tensor(w)
    args = [xt, wt]
    if bias is not None:
        args.append(as_tensor(bias))

    def fn(x, wv, *rest):
        y = jnp.einsum("snd,sdo->sno", x, wv)
        if rest:
            y = y + rest[0][:, None, :]
        if act == "relu":
            y = jax.nn.relu(y)
        elif act == "sigmoid":
            y = jax.nn.sigmoid(y)
        elif act == "tanh":
            y = jnp.tanh(y)
        elif act is not None:
            raise ValueError(f"unsupported act {act!r}")
        return y

    return apply(fn, *args, name="batch_fc")


# ------------------------------------------------------------ correlation
def correlation(x, y, pad_size: int, kernel_size: int, max_displacement: int,
                stride1: int, stride2: int, corr_type_multiply: int = 1,
                name=None):
    """FlowNet correlation cost volume over NCHW pairs.

    ``out[n, (tj,ti), oh, ow]`` = mean over the kernel window and channels
    of ``x[.., h1+j, w1+i] * y[.., h1+tj*stride2+j, w1+ti*stride2+i]``
    with ``h1 = oh*stride1 + max_displacement`` on zero-padded inputs;
    displacement channels enumerate ``tj, ti`` in
    ``[-max_displacement/stride2, +max_displacement/stride2]``
    row-major.

    reference: incubate/layers/nn.py:1003 + gpu/correlation_kernel.cu
    (correlation_forward; CPU raises Unimplemented there — this jnp
    version runs on every backend, a strict capability win).
    """
    xt, yt = as_tensor(x), as_tensor(y)
    krad = (kernel_size - 1) // 2
    drad = max_displacement // stride2
    n, c, h, w = (int(s) for s in xt.shape)
    hp, wp = h + 2 * pad_size, w + 2 * pad_size
    border = krad + max_displacement
    out_h = int(math.ceil(float(hp - 2 * border) / stride1))
    out_w = int(math.ceil(float(wp - 2 * border) / stride1))
    if out_h <= 0 or out_w <= 0:
        raise ValueError("correlation output is empty; check pad/kernel/"
                         "displacement geometry")
    nelems = kernel_size * kernel_size * c
    marg = drad * stride2

    def fn(xv, yv):
        xp = jnp.pad(xv, ((0, 0), (0, 0), (pad_size, pad_size),
                          (pad_size, pad_size)))
        # extra margin so displaced windows never index out of bounds
        yp = jnp.pad(yv, ((0, 0), (0, 0),
                          (pad_size + marg, pad_size + marg),
                          (pad_size + marg, pad_size + marg)))
        planes = []
        for tj in range(-drad, drad + 1):
            for ti in range(-drad, drad + 1):
                dy, dx = tj * stride2 + marg, ti * stride2 + marg
                ysh = lax.dynamic_slice(
                    yp, (0, 0, dy, dx), (n, c, hp, wp))
                prod = jnp.sum(xp * ysh, axis=1)          # (N, Hp, Wp)
                pk = jnp.pad(prod, ((0, 0), (krad, krad), (krad, krad)))
                win = lax.reduce_window(
                    pk, 0.0, lax.add,
                    (1, kernel_size, kernel_size), (1, 1, 1), "VALID")
                rows = max_displacement + stride1 * jnp.arange(out_h)
                cols = max_displacement + stride1 * jnp.arange(out_w)
                planes.append(win[:, rows[:, None], cols[None, :]]
                              / nelems)
        return jnp.stack(planes, axis=1)                   # (N, D^2, oh, ow)

    return apply(fn, xt, yt, name="correlation")


# ----------------------------------------------------- legacy kernel ops
def affine_channel(x, scale, bias, data_layout: str = "NCHW", name=None):
    """Per-channel affine: ``y = x * scale[c] + bias[c]``.

    reference: cpu/affine_channel_kernel.cc (kernel-only in this
    snapshot; NCHW/NHWC layouts).
    """
    xt, st, bt = as_tensor(x), as_tensor(scale), as_tensor(bias)
    ch_axis = 1 if data_layout in ("NCHW", "NCDHW") else -1

    def fn(v, s, b):
        shape = [1] * v.ndim
        shape[ch_axis] = -1
        return v * s.reshape(shape) + b.reshape(shape)

    return apply(fn, xt, st, bt, name="affine_channel")


def add_position_encoding(x, alpha: float, beta: float, name=None):
    """Scaled sinusoidal position encoding over (B, L, D) input:
    ``out[..., k] = x*alpha + sin(pos / 10000^(k/(D/2-1)))*beta`` for the
    first half of D, ``cos`` for the second half.

    reference: cpu/add_position_encoding_kernel.cc (kernel-only; the
    LoD 2-D form maps to padded 3-D here).
    """
    xt = as_tensor(x)
    if xt.ndim != 3:
        raise ValueError("add_position_encoding expects (batch, seq, dim)")
    d = int(xt.shape[-1])
    if d % 2:
        raise ValueError("feature size must be even")
    half = d // 2

    def fn(v):
        pos = jnp.arange(v.shape[1], dtype=jnp.float32)[:, None]
        k = jnp.arange(half, dtype=jnp.float32)[None, :]
        div = jnp.power(10000.0, k / (half - 1)) if half > 1 \
            else jnp.full((1, 1), 10000.0)
        val = pos / div                                    # (L, half)
        enc = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=-1)
        return v * alpha + enc[None].astype(v.dtype) * beta

    return apply(fn, xt, name="add_position_encoding")


def box_clip(input, im_info, pixel_offset: bool = True, name=None):
    """Clip (B, M, 4) boxes to per-image bounds derived from ``im_info``
    rows ``[h, w, scale]``: width/height are ``round(w/scale)`` minus a
    1-pixel offset. reference: impl/box_clip_kernel_impl.h
    (ClipTiledBoxes; the LoD slice loop maps to the leading batch dim).
    """
    bt, it = as_tensor(input), as_tensor(im_info)

    def fn(boxes, info):
        offset = 1.0 if pixel_offset else 0.0
        im_w = jnp.round(info[:, 1] / info[:, 2]) - offset
        im_h = jnp.round(info[:, 0] / info[:, 2]) - offset
        shape = (-1,) + (1,) * (boxes.ndim - 2)
        im_w, im_h = im_w.reshape(shape), im_h.reshape(shape)
        x1 = jnp.minimum(jnp.clip(boxes[..., 0], 0, None), im_w)
        y1 = jnp.minimum(jnp.clip(boxes[..., 1], 0, None), im_h)
        x2 = jnp.minimum(jnp.clip(boxes[..., 2], 0, None), im_w)
        y2 = jnp.minimum(jnp.clip(boxes[..., 3], 0, None), im_h)
        return jnp.stack([x1, y1, x2, y2], axis=-1)

    return apply(fn, bt, it, name="box_clip")


def bipartite_match(dist_matrix, match_type: str = "bipartite",
                    dist_threshold: Optional[float] = None, name=None):
    """Greedy bipartite matching on a (row, col) distance matrix — each
    round matches the globally-largest remaining (row, col) pair; with
    ``match_type='per_prediction'`` unmatched columns then take their
    argmax row if it clears ``dist_threshold``.

    Returns ``(match_indices, match_dist)`` of shape (1, col) (or
    (B, col) for a batched 3-D input): column j's matched row or -1.

    reference: cpu/bipartite_match_kernel.cc (BipartiteMatch greedy path
    + ArgMaxMatch). Host-side numpy — the output feeds CPU-side target
    assignment, not the hot path.
    """
    dm = _np(dist_matrix).astype(np.float64)
    batched = dm.ndim == 3
    mats = dm if batched else dm[None]
    eps = 1e-6
    all_idx, all_dist = [], []
    for mat in mats:
        row, col = mat.shape
        midx = np.full((col,), -1, np.int32)
        mdist = np.zeros((col,), np.float32)
        pool = mat.copy()
        row_free = np.ones((row,), bool)
        while row_free.any():
            sub = np.where(row_free[:, None] & (midx[None, :] == -1),
                           pool, -np.inf)
            sub = np.where(sub < eps, -np.inf, sub)
            if not np.isfinite(sub).any():
                break
            r, cc = np.unravel_index(np.argmax(sub), sub.shape)
            midx[cc] = r
            mdist[cc] = mat[r, cc]
            row_free[r] = False
        if match_type == "per_prediction":
            thr = 0.5 if dist_threshold is None else dist_threshold
            for j in range(col):
                if midx[j] != -1:
                    continue
                colv = mat[:, j]
                r = int(np.argmax(colv))
                if colv[r] >= thr and colv[r] >= eps:
                    midx[j] = r
                    mdist[j] = colv[r]
        elif match_type != "bipartite":
            raise ValueError(f"unknown match_type {match_type!r}")
        all_idx.append(midx)
        all_dist.append(mdist)
    ii, dd = np.stack(all_idx), np.stack(all_dist)
    return Tensor(ii), Tensor(dd)


def ctc_align(input, input_length, blank: int = 0,
              merge_repeated: bool = True, padding_value: int = 0,
              name=None):
    """CTC decode alignment: drop blanks (and merged repeats) from each
    row of (B, L) int tokens, left-compact, pad with ``padding_value``.
    Returns ``(output, output_length)``.

    reference: impl/ctc_align_kernel_impl.h (padded-tensor branch; the
    LoD branch is the legacy flat form).
    """
    xt, lt = as_tensor(input), as_tensor(input_length)

    def fn(v, ln):
        L = v.shape[1]
        pos = jnp.arange(L)[None, :]
        in_len = ln.reshape(-1, 1).astype(jnp.int32)
        prev = jnp.concatenate(
            [jnp.full((v.shape[0], 1), -1, v.dtype), v[:, :-1]], axis=1)
        keep = (v != blank) & (pos < in_len)
        if merge_repeated:
            keep &= v != prev
        order = jnp.argsort(~keep, axis=1, stable=True)
        gathered = jnp.take_along_axis(v, order, axis=1)
        out_len = keep.sum(axis=1)
        out = jnp.where(pos < out_len[:, None], gathered,
                        jnp.asarray(padding_value, v.dtype))
        return out, out_len.astype(v.dtype)

    return apply(fn, xt, lt, name="ctc_align", multi_out=True)


def im2sequence(input, kernels: Sequence[int], strides: Sequence[int] =
                (1, 1), paddings: Sequence[int] = (0, 0, 0, 0), name=None):
    """Image to patch-sequence: (N, C, H, W) -> (N*oh*ow, C*kh*kw), each
    row one (C, kh, kw) patch, positions row-major, batches contiguous.
    ``paddings`` is (up, left, down, right).

    reference: impl/im2sequence_kernel_impl.h (static-shape branch; the
    real-size LoD branch is per-image crop — slice before calling).
    """
    xt = as_tensor(input)
    kh, kw = kernels
    sh, sw = strides
    pu, pl, pd, pr = paddings
    n, c, h, w = (int(s) for s in xt.shape)
    oh = (h + pu + pd - kh) // sh + 1
    ow = (w + pl + pr - kw) // sw + 1

    def fn(v):
        vp = jnp.pad(v, ((0, 0), (0, 0), (pu, pd), (pl, pr)))
        rows = []
        for kj in range(kh):
            for ki in range(kw):
                rows.append(lax.slice(
                    vp, (0, 0, kj, ki),
                    (n, c, kj + (oh - 1) * sh + 1, ki + (ow - 1) * sw + 1),
                    (1, 1, sh, sw)))                     # (N, C, oh, ow)
        pat = jnp.stack(rows, axis=2).reshape(n, c, kh, kw, oh, ow)
        pat = pat.transpose(0, 4, 5, 1, 2, 3)
        return pat.reshape(n * oh * ow, c * kh * kw)

    return apply(fn, xt, name="im2sequence")


# -------------------------------------------------------------- chunk_eval
_CHUNK_SCHEMES = {
    # num_tag_types, (begin, inside, end, single)
    "IOB": (2, (0, 1, -1, -1)),
    "IOE": (2, (-1, 0, 1, -1)),
    "IOBES": (4, (0, 1, 2, 3)),
    "plain": (1, (-1, -1, -1, -1)),
}


def _chunk_segments(labels, num_chunk_types, scheme):
    num_tag, (tb, ti_, te, ts) = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types

    def chunk_end(ptag, ptype, tag, typ):
        if ptype == other:
            return False
        if typ == other or typ != ptype:
            return True
        if ptag == tb or ptag == ti_:
            return tag in (tb, ts)
        if ptag in (te, ts):
            return True
        return False

    def chunk_begin(ptag, ptype, tag, typ):
        if ptype == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptype:
            return True
        if tag == tb or tag == ts:
            return True
        if tag in (ti_, te):
            return ptag in (te, ts)
        return False

    segs = []
    in_chunk, start = False, 0
    tag, typ = -1, other
    for i, lab in enumerate(labels):
        ptag, ptype = tag, typ
        lab = int(lab)
        if lab > num_chunk_types * num_tag:
            raise ValueError(f"label {lab} out of range")
        tag, typ = lab % num_tag, lab // num_tag
        if in_chunk and chunk_end(ptag, ptype, tag, typ):
            segs.append((start, i - 1, ptype))
            in_chunk = False
        if chunk_begin(ptag, ptype, tag, typ):
            start, in_chunk = i, True
    if in_chunk:
        segs.append((start, len(labels) - 1, typ))
    return segs


def chunk_eval(input, label, chunk_scheme: str, num_chunk_types: int,
               excluded_chunk_types: Optional[Sequence[int]] = None,
               seq_length=None, name=None):
    """Chunking (NER) precision/recall/F1 over (B, L) int64 tag batches
    with per-row ``seq_length``. Labels encode ``type * num_tag_types +
    tag`` with scheme IOB / IOE / IOBES / plain; type ``num_chunk_types``
    is "other/outside".

    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks).

    reference: impl/chunk_eval_kernel_impl.h (GetSegments / ChunkBegin /
    ChunkEnd / EvalOneSeq). Host-side numpy metric.
    """
    if chunk_scheme not in _CHUNK_SCHEMES:
        raise ValueError(f"unknown chunk scheme {chunk_scheme!r}")
    inf = _np(input)
    lab = _np(label)
    if inf.ndim == 1:
        inf, lab = inf[None], lab[None]
    if seq_length is None:
        lens = np.full((inf.shape[0],), inf.shape[1], np.int64)
    else:
        lens = _np(seq_length).reshape(-1).astype(np.int64)
    excl = set(excluded_chunk_types or ())
    n_inf = n_lab = n_cor = 0
    for b in range(inf.shape[0]):
        L = int(lens[b])
        segs_o = _chunk_segments(inf[b, :L], num_chunk_types, chunk_scheme)
        segs_l = _chunk_segments(lab[b, :L], num_chunk_types, chunk_scheme)
        i = j = 0
        while i < len(segs_o) and j < len(segs_l):
            if segs_o[i] == segs_l[j] and segs_o[i][2] not in excl:
                n_cor += 1
            if segs_o[i][1] < segs_l[j][1]:
                i += 1
            elif segs_o[i][1] > segs_l[j][1]:
                j += 1
            else:
                i += 1
                j += 1
        n_inf += sum(1 for s in segs_o if s[2] not in excl)
        n_lab += sum(1 for s in segs_l if s[2] not in excl)
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if n_cor else 0.0
    return (Tensor(np.float32(prec)), Tensor(np.float32(rec)),
            Tensor(np.float32(f1)), Tensor(np.int64(n_inf)),
            Tensor(np.int64(n_lab)), Tensor(np.int64(n_cor)))


# ------------------------------------------------------------ detection_map
def detection_map(detect_res, gt_label, class_num: int,
                  background_label: int = 0,
                  overlap_threshold: float = 0.5,
                  evaluate_difficult: bool = True,
                  ap_version: str = "integral", state=None, name=None):
    """VOC-style detection mAP with streaming accumulation.

    ``detect_res``: per-image list of (n_i, 6) arrays
    ``[label, score, xmin, ymin, xmax, ymax]`` (the reference's LoD rows
    become a python list — TPU-native host metric). ``gt_label``:
    per-image list of (m_i, 5) ``[label, xmin, ymin, xmax, ymax]`` or
    (m_i, 6) with a ``difficult`` flag after label. ``state`` is the
    previous call's returned state for cross-batch accumulation (the
    kernel's HasState/PosCount streaming inputs). Returns
    ``(mAP_tensor, state)``.

    reference: cpu/detection_map_kernel.cc (CalcTrueAndFalsePositive /
    CalcMAP; pred boxes are clipped to [0,1] before the Jaccard overlap,
    matching ClipBBox — coordinates are normalized).
    """
    if ap_version not in ("integral", "11point"):
        raise ValueError(f"unknown ap_version {ap_version!r}")
    label_pos = dict(state[0]) if state else {}
    true_pos = {k: list(v) for k, v in state[1].items()} if state else {}
    false_pos = {k: list(v) for k, v in state[2].items()} if state else {}

    def _iou(b1, b2):
        if (b2[0] > b1[2] or b2[2] < b1[0] or b2[1] > b1[3]
                or b2[3] < b1[1]):
            return 0.0
        ix1, iy1 = max(b1[0], b2[0]), max(b1[1], b2[1])
        ix2, iy2 = min(b1[2], b2[2]), min(b1[3], b2[3])
        inter = (ix2 - ix1) * (iy2 - iy1)
        a1 = (b1[2] - b1[0]) * (b1[3] - b1[1])
        a2 = (b2[2] - b2[0]) * (b2[3] - b2[1])
        return inter / (a1 + a2 - inter)

    gts, dets = [], []
    for img_gt, img_det in zip(gt_label, detect_res):
        g = _np(img_gt).astype(np.float64).reshape(-1, _np(img_gt).shape[-1]
                                                   if _np(img_gt).size else 5)
        d = _np(img_det).astype(np.float64).reshape(
            -1, 6) if _np(img_det).size else np.zeros((0, 6))
        by_label: dict = {}
        for r in g:
            if len(r) == 6:
                lab, diff, box = int(r[0]), bool(r[1]), r[2:6]
            else:
                lab, diff, box = int(r[0]), False, r[1:5]
            by_label.setdefault(lab, []).append((box, diff))
        gts.append(by_label)
        dby: dict = {}
        for r in d:
            dby.setdefault(int(r[0]), []).append((float(r[1]), r[2:6]))
        dets.append(dby)

    # label_pos_count (reference: first loop of CalcTrueAndFalsePositive)
    for by_label in gts:
        for lab, boxes in by_label.items():
            cnt = len(boxes) if evaluate_difficult else \
                sum(1 for _, diff in boxes if not diff)
            if cnt:
                label_pos[lab] = label_pos.get(lab, 0) + cnt

    for by_label, dby in zip(gts, dets):
        for lab, preds in dby.items():
            if lab not in by_label:
                for score, _ in preds:
                    true_pos.setdefault(lab, []).append((score, 0))
                    false_pos.setdefault(lab, []).append((score, 1))
                continue
            matched = by_label[lab]
            visited = [False] * len(matched)
            for score, box in sorted(preds, key=lambda p: -p[0]):
                box = np.clip(box, 0.0, 1.0)
                ious = [_iou(box, m[0]) for m in matched]
                mi = int(np.argmax(ious)) if ious else 0
                if ious and ious[mi] > overlap_threshold:
                    if evaluate_difficult or not matched[mi][1]:
                        hit = 0 if visited[mi] else 1
                        visited[mi] |= bool(hit)
                        true_pos.setdefault(lab, []).append((score, hit))
                        false_pos.setdefault(lab, []).append(
                            (score, 1 - hit))
                else:
                    true_pos.setdefault(lab, []).append((score, 0))
                    false_pos.setdefault(lab, []).append((score, 1))

    # CalcMAP
    m_ap, count = 0.0, 0
    for lab, num_pos in label_pos.items():
        # skip the background CLASS (the reference kernel compares the
        # positive COUNT to background_label — detection_map_kernel.cc
        # CalcMAP `label_num_pos == background_label` — which includes
        # background in mAP and drops classes whose count collides;
        # deliberate deviation to the correct VOC semantics)
        if lab == background_label:
            continue
        if lab not in true_pos:
            count += 1
            continue
        tp = sorted(true_pos[lab], key=lambda p: -p[0])
        fp = sorted(false_pos[lab], key=lambda p: -p[0])
        tp_sum = np.cumsum([f for _, f in tp])
        fp_sum = np.cumsum([f for _, f in fp])
        prec = tp_sum / np.maximum(tp_sum + fp_sum, 1e-12)
        rec = tp_sum / num_pos
        if ap_version == "11point":
            maxp = np.zeros(11)
            start = len(rec) - 1
            for j in range(10, -1, -1):
                for i in range(start, -1, -1):
                    if rec[i] < j / 10.0:
                        start = i
                        if j > 0:
                            maxp[j - 1] = maxp[j]
                        break
                    maxp[j] = max(maxp[j], prec[i])
            m_ap += maxp.sum() / 11
        else:
            prev_r, ap = 0.0, 0.0
            for p, r in zip(prec, rec):
                if abs(r - prev_r) > 1e-6:
                    ap += p * abs(r - prev_r)
                prev_r = r
            m_ap += ap
        count += 1
    if count:
        m_ap /= count
    return Tensor(np.float32(m_ap)), (dict(label_pos),
                                      {k: list(v) for k, v in
                                       true_pos.items()},
                                      {k: list(v) for k, v in
                                       false_pos.items()})


# ------------------------------------------------------- attention_lstm
_LSTM_ACTS = {
    "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
    "relu": jax.nn.relu, "identity": (lambda v: v),
}


def attention_lstm(x, c0, h0=None, attention_weight=None,
                   attention_bias=None, attention_scalar=None,
                   attention_scalar_bias=None, lstm_weight=None,
                   lstm_bias=None, lengths=None,
                   gate_activation: str = "sigmoid",
                   cell_activation: str = "tanh",
                   candidate_activation: str = "tanh", name=None):
    """Fused attention + LSTM over padded (B, L, M) sequences.

    Every step re-attends over the whole sequence: scores =
    softmax(relu(x @ aw[:M] + ab + prev_cell . aw[M:]) [* scalar + sb]),
    the attention-pooled input drives one LSTM step with gate layout
    ``[forget, input, output, candidate]`` in ``lstm_weight
    ((D+M), 4D)`` (hidden rows first, input rows after — reference
    kernel's `lstm_w_data + D*D4` split). Returns ``(hidden (B, L, D),
    cell (B, L, D))``, zero-padded past each length.

    reference: paddle/phi/kernels/cpu/attention_lstm_kernel.cc
    (AttentionLSTMKernel; CPU-only legacy fusion — LoD becomes padded +
    ``lengths``). lax.scan over steps: one compiled program, grads via
    jax autodiff (the reference op is forward-only).
    """
    for act in (gate_activation, cell_activation, candidate_activation):
        if act not in _LSTM_ACTS:
            raise ValueError(f"unsupported activation {act!r}")
    if attention_scalar_bias is not None and attention_scalar is None:
        # the kernel only reads the bias inside the scalar branch —
        # accepting it alone would silently ignore a user parameter
        raise ValueError("attention_scalar_bias requires attention_scalar")
    xt = as_tensor(x)
    if xt.ndim != 3:
        raise ValueError("attention_lstm expects (batch, max_len, M) + "
                         "lengths (LoD-free padded form)")
    B, L, M = (int(s) for s in xt.shape)
    aw = as_tensor(attention_weight)
    lw = as_tensor(lstm_weight)
    D = int(lw.shape[1]) // 4
    args = [xt, as_tensor(c0), aw, lw, as_tensor(lstm_bias)]
    opt = {"h0": h0, "ab": attention_bias, "asc": attention_scalar,
           "asb": attention_scalar_bias, "lens": lengths}
    keys = [k for k, v in opt.items() if v is not None]
    args += [as_tensor(opt[k]) for k in keys]
    act_g = _LSTM_ACTS[gate_activation]
    act_c = _LSTM_ACTS[cell_activation]
    act_d = _LSTM_ACTS[candidate_activation]

    def fn(xv, c0v, awv, lwv, lbv, *rest):
        o = dict(zip(keys, rest))
        ln = o["lens"].reshape(-1).astype(jnp.int32) if "lens" in o \
            else jnp.full((B,), L, jnp.int32)
        mask = jnp.arange(L)[None, :] < ln[:, None]          # (B, L)
        atted = xv.astype(jnp.float32) @ awv[:M].reshape(M)  # (B, L)
        if "ab" in o:
            atted = atted + o["ab"].reshape(())
        h_init = o["h0"].astype(jnp.float32) if "h0" in o else \
            jnp.zeros((B, D), jnp.float32)
        w_h, w_x = lwv[:D].astype(jnp.float32), lwv[D:].astype(jnp.float32)

        def step(carry, _):
            h_prev, c_prev = carry
            s = atted + (c_prev @ awv[M:].reshape(D, 1)[:, 0])[:, None]
            s = jax.nn.relu(s)
            if "asc" in o:
                s = s * o["asc"].reshape(())
                if "asb" in o:
                    s = jax.nn.relu(s + o["asb"].reshape(()))
                else:
                    s = jax.nn.relu(s)
            # finite mask value, not -inf: a zero-length row would make
            # softmax NaN, and 0 * NaN = NaN poisons the summed weight
            # grads of the whole batch in the scan backward
            s = jnp.where(mask, s, -1e30)
            attn = jax.nn.softmax(s, axis=1)                 # (B, L)
            attn = jnp.where(mask & (ln > 0)[:, None], attn, 0.0)
            pooled = jnp.einsum("bl,blm->bm", attn,
                                xv.astype(jnp.float32))      # (B, M)
            gates = pooled @ w_x + h_prev @ w_h + lbv.reshape(-1)
            f = act_g(gates[:, :D])
            i = act_g(gates[:, D:2 * D])
            og = act_g(gates[:, 2 * D:3 * D])
            cand = act_d(gates[:, 3 * D:])
            c_new = f * c_prev + i * cand
            h_new = act_c(c_new) * og
            return (h_new, c_new), (h_new, c_new)

        (_, _), (hs, cs) = lax.scan(step, (h_init, c0v.astype(jnp.float32)),
                                    None, length=L)
        hs = jnp.swapaxes(hs, 0, 1)                          # (B, L, D)
        cs = jnp.swapaxes(cs, 0, 1)
        hs = jnp.where(mask[..., None], hs, 0).astype(xv.dtype)
        cs = jnp.where(mask[..., None], cs, 0).astype(xv.dtype)
        return hs, cs

    return apply(fn, *args, name="attention_lstm", multi_out=True)


# --------------------------------------------------- match_matrix_tensor
def match_matrix_tensor(x, y, w, dim_t: int, x_lengths=None,
                        y_lengths=None, name=None):
    """Bilinear text-matching tensor: for each pair of rows
    ``out[b, t, i, j] = x[b, i] @ W[:, t, :] @ y[b, j]`` over padded
    (B, Lx, D) x and (B, Ly, D) y with ``w (D, dim_t, D)`` (or the
    reference's flattened ``(D, dim_t*D)``); positions past the lengths
    are zero.

    reference: paddle/phi/kernels/cpu/match_matrix_tensor_kernel.cc
    (x @ w as one gemm, then per-(batch, t) gemm against y^T — here one
    einsum the MXU tiles directly; LoD pairs become the padded batch).
    """
    xt, yt, wt = as_tensor(x), as_tensor(y), as_tensor(w)
    if xt.ndim != 3 or yt.ndim != 3:
        raise ValueError("match_matrix_tensor expects padded (B, L, D) "
                         "inputs + lengths")
    d = int(xt.shape[-1])
    args = [xt, yt, wt]
    keys = []
    if x_lengths is not None:
        keys.append("lx")
        args.append(as_tensor(x_lengths))
    if y_lengths is not None:
        keys.append("ly")
        args.append(as_tensor(y_lengths))

    def fn(xv, yv, wv, *rest):
        o = dict(zip(keys, rest))
        w3 = wv.reshape(d, dim_t, d).astype(jnp.float32)
        out = jnp.einsum("bid,dte,bje->btij", xv.astype(jnp.float32),
                         w3, yv.astype(jnp.float32))
        if "lx" in o:
            mi = jnp.arange(out.shape[2])[None, None, :, None] < \
                o["lx"].reshape(-1, 1, 1, 1)
            out = jnp.where(mi, out, 0)
        if "ly" in o:
            mj = jnp.arange(out.shape[3])[None, None, None, :] < \
                o["ly"].reshape(-1, 1, 1, 1)
            out = jnp.where(mj, out, 0)
        return out.astype(xv.dtype)

    return apply(fn, *args, name="match_matrix_tensor")
