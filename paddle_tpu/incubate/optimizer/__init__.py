"""reference: python/paddle/incubate/optimizer/ — DistributedFusedLamb
(distributed_fused_lamb.py), LookAhead, ModelAverage."""
from .distributed_fused_lamb import DistributedFusedLamb  # noqa: F401
from .modelaverage import ModelAverage  # noqa: F401
from .lookahead import LookAhead  # noqa: F401
from .legacy import Ftrl, Dpsgd  # noqa: F401
