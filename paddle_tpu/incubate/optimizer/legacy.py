"""Legacy per-kernel optimizers kept registered in the reference op set
(paddle/phi/ops/yaml/ops.yaml: ftrl, dpsgd) whose python wrappers lived in
the removed fluid.optimizer module — parity home here, following the
framework's functional update-rule contract (optimizer/optimizer.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer


class Ftrl(Optimizer):
    """FTRL-proximal (McMahan et al., "Ad Click Prediction").

    Update (reference: paddle/phi/kernels/impl/ftrl_kernel_impl.h
    FTRLOpKernel, incl. the kernel's own l1/l2 += 1e-10 bias):
        new_acc = s + g^2
        linear += g - (new_acc^{-p} - s^{-p}) / lr * param
        param   = (l1*sign(linear) - linear) /
                  (new_acc^{-p}/lr + 2*l2)   if |linear| > l1 else 0
        s       = new_acc
    """

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _slots(self):
        return ("squared_accum", "linear_accum")

    def _context(self):
        return {"l1": self._l1 + 1e-10, "l2": self._l2 + 1e-10,
                "p": self._lr_power}

    def _update_rule(self, p, g, state, lr, ctx):
        l1, l2, pw = ctx["l1"], ctx["l2"], ctx["p"]
        g = g.astype(jnp.float32)
        s = state["squared_accum"]
        new_acc = s + g * g
        # pow(-pw) on s==0 with pw=-0.5 is sqrt(0)=0; general powers keep
        # the kernel's pow semantics
        lin = state["linear_accum"] + g - \
            (jnp.power(new_acc, -pw) - jnp.power(s, -pw)) / lr * p
        x = l1 * jnp.sign(lin) - lin
        y = jnp.power(new_acc, -pw) / lr + 2.0 * l2
        state["squared_accum"] = new_acc
        state["linear_accum"] = lin
        return jnp.where(jnp.abs(lin) > l1, x / y, 0.0).astype(p.dtype), \
            state


class Dpsgd(Optimizer):
    """Differentially-private SGD (Abadi et al., CCS'16).

    Per step and per parameter tensor: scale the gradient down when its
    L2 norm exceeds ``clip`` (scale = norm/clip), add gaussian noise
    ``N(0, sigma^2)/batch_size``, and apply SGD.

    reference: paddle/phi/kernels/cpu/dpsgd_kernel.cc (DpsgdOpKernel).
    Deviations (MIGRATION.md): noise comes from the JAX counter-based
    PRNG (seeded, reproducible; keyed per parameter AND per step), and is
    drawn PER COORDINATE — the reference kernel adds one shared scalar
    per tensor per step, which makes the noise rank-1/correlated and
    voids the DP-SGD privacy analysis (Abadi et al. require independent
    N(0, sigma^2 I) coordinates).
    """

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, seed=0, parameters=None, name=None):
        super().__init__(learning_rate, parameters, None, None)
        self._clip, self._bs, self._sigma = clip, batch_size, sigma
        self._seed = seed
        self._noise_ord = 0

    def _slots(self):
        return ()

    def _context(self):
        # reset the per-step parameter counter here: _context runs once
        # per step()/functional build, BEFORE the per-parameter
        # _update_rule loop, and never touches traced values (a traced
        # ctx["step"] comparison would break under jax.jit)
        self._noise_ord = 0
        return {"clip": self._clip, "bs": self._bs, "sigma": self._sigma,
                "seed": self._seed}

    def _update_rule(self, p, g, state, lr, ctx):
        g = g.astype(jnp.float32)
        norm = jnp.sqrt(jnp.sum(g * g))
        scale = jnp.where(norm > ctx["clip"], norm / ctx["clip"], 1.0)
        # key folds in the parameter's position in the (fixed) update
        # order so tensors never share a noise draw — auto-generated
        # tensor names are not stable across runs, positions are — and
        # the draw is per-coordinate (see docstring). The position is a
        # python-level (trace-time) constant; ctx["step"] may be traced.
        idx = self._noise_ord
        self._noise_ord += 1
        key = jax.random.fold_in(jax.random.key(ctx["seed"]),
                                 jnp.asarray(ctx["step"], jnp.uint32))
        key = jax.random.fold_in(key, jnp.uint32(idx))
        noise = jax.random.normal(key, g.shape) * ctx["sigma"]
        return (p - lr * (g / scale + noise / ctx["bs"])).astype(p.dtype), \
            state
