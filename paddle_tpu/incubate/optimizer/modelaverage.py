"""ModelAverage (reference: python/paddle/incubate/optimizer/
modelaverage.py:42 + average_accumulates_ kernel): sliding-window average
of parameters applied at evaluation time.

Window semantics (reference docstring): accumulation restarts when
  num_accumulates >= min_average_window and
  num_accumulates >= min(max_average_window,
                         num_updates * average_window_rate)
The rotated window (sum_2/old_num) keeps the previous window's sums so the
applied average always spans at least min_average_window samples.
"""
from __future__ import annotations

import contextlib
from typing import Dict

import numpy as np
import jax.numpy as jnp


class ModelAverage:
    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.average_window_rate = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        if parameters is None:
            raise ValueError(
                "ModelAverage needs explicit parameters (there is no "
                "global program to collect them from): pass "
                "parameters=model.parameters()")
        self._params = [p for p in parameters if not p.stop_gradient]
        self._sum_1: Dict[int, jnp.ndarray] = {
            id(p): jnp.zeros(tuple(p.shape), jnp.float32)
            for p in self._params}
        self._sum_2 = {id(p): jnp.zeros(tuple(p.shape), jnp.float32)
                       for p in self._params}
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._num_updates = 0
        self._backup = None

    # ---- training-side accumulation ----
    def step(self):
        """Accumulate the current parameter values (call after the real
        optimizer's step; reference: average_accumulates_ op)."""
        self._num_updates += 1
        self._num_accumulates += 1
        for p in self._params:
            self._sum_1[id(p)] = self._sum_1[id(p)] + \
                p._value.astype(jnp.float32)
        window = min(self.max_average_window,
                     self._num_updates * self.average_window_rate)
        if self._num_accumulates >= self.min_average_window and \
                self._num_accumulates >= window:
            # rotate: the finished window becomes the 'old' window
            self._sum_2 = dict(self._sum_1)
            self._old_num_accumulates = self._num_accumulates
            self._sum_1 = {k: jnp.zeros_like(v)
                           for k, v in self._sum_1.items()}
            self._num_accumulates = 0

    def minimize(self, *a, **k):
        self.step()

    def clear_grad(self):
        pass

    # ---- evaluation-side swap ----
    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap parameters to their windowed average (reference:
        ModelAverage.apply)."""
        total = self._num_accumulates + self._old_num_accumulates
        if total == 0:
            yield
            return
        self._backup = {id(p): p._value for p in self._params}
        for p in self._params:
            avg = (self._sum_1[id(p)] + self._sum_2[id(p)]) / total
            p._inplace_assign(avg.astype(self._backup[id(p)].dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        """reference: ModelAverage.restore."""
        if self._backup is None:
            return
        for p in self._params:
            p._inplace_assign(self._backup[id(p)])
        self._backup = None

    # ---- checkpoint state (reference persists the sums/counters as
    # optimizer accumulators) ----
    def state_dict(self):
        names = {id(p): getattr(p, "name", str(i))
                 for i, p in enumerate(self._params)}
        return {
            "sum_1": {names[k]: np.asarray(v)
                      for k, v in self._sum_1.items()},
            "sum_2": {names[k]: np.asarray(v)
                      for k, v in self._sum_2.items()},
            "num_accumulates": self._num_accumulates,
            "old_num_accumulates": self._old_num_accumulates,
            "num_updates": self._num_updates,
        }

    def set_state_dict(self, sd):
        by_name = {getattr(p, "name", str(i)): p
                   for i, p in enumerate(self._params)}
        for attr, key in (("_sum_1", "sum_1"), ("_sum_2", "sum_2")):
            store = getattr(self, attr)
            for name, v in sd.get(key, {}).items():
                p = by_name.get(name)
                if p is not None:
                    store[id(p)] = jnp.asarray(np.asarray(v), jnp.float32)
        self._num_accumulates = int(sd.get("num_accumulates", 0))
        self._old_num_accumulates = int(sd.get("old_num_accumulates", 0))
        self._num_updates = int(sd.get("num_updates", 0))
