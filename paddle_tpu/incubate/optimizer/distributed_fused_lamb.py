"""DistributedFusedLamb (reference: python/paddle/incubate/optimizer/
distributed_fused_lamb.py — flattens params into fused buffers and shards
LAMB state across ranks).

TPU-native: LAMB math over the whole parameter pytree in one jitted
update; state sharding comes from the surrounding pjit/sharding rules
(ZeRO semantics are declared, not bookkept), so "fused + distributed" is
the default execution, not a special optimizer. This subclass exists for
API parity and adds the global-norm clipping the reference applies.
"""
from __future__ import annotations

from ...optimizer.optimizers import Lamb


class DistributedFusedLamb(Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=None, **kw):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay,
                         beta1=beta1, beta2=beta2, epsilon=epsilon,
                         parameters=parameters, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=
                         exclude_from_weight_decay_fn)
