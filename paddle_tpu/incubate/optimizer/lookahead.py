"""LookAhead optimizer wrapper (reference: python/paddle/incubate/
optimizer/lookahead.py — Zhang et al. 2019: k fast steps with an inner
optimizer, then slow weights interpolate toward the fast weights)."""
from __future__ import annotations

from typing import Dict

import numpy as np
import jax.numpy as jnp


class LookAhead:
    """Wraps an inner optimizer; every ``k`` steps the slow copy moves
    ``alpha`` of the way to the fast weights and the fast weights reset to
    the slow copy."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be >= 1")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        # slow weights start at the initial parameter values
        self._slow: Dict[int, jnp.ndarray] = {
            id(p): p._value.astype(jnp.float32) for p in self._params()}

    def _params(self):
        return [p for p in self.inner_optimizer._parameter_list
                if not p.stop_gradient]

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        masters = self.inner_optimizer._accumulators.get("master", {})
        for p in self._params():
            slow = self._slow.get(id(p))
            if slow is None:
                # param unfrozen after construction: joins the slow
                # trajectory from its current value
                slow = p._value.astype(jnp.float32)
            slow = slow + self.alpha * (
                p._value.astype(jnp.float32) - slow)
            self._slow[id(p)] = slow
            p._inplace_assign(slow.astype(p._value.dtype))
            # low-precision params: the inner optimizer's fp32 master is
            # its source of truth — sync it or the next step undoes us
            m = masters.get(id(p))
            if m is not None:
                m._inplace_assign(slow)

    def minimize(self, loss, *a, **k):
        # codebase contract: the caller has already run loss.backward()
        self.step()

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["@lookahead_step"] = self._step_count
        names = self.inner_optimizer._param_names()
        sd["@lookahead_slow"] = {
            names.get(pid, str(pid)): np.asarray(v)
            for pid, v in self._slow.items()}
        return sd

    def set_state_dict(self, state_dict):
        state_dict = dict(state_dict)  # never mutate the caller's dict
        self._step_count = int(state_dict.pop("@lookahead_step", 0))
        slow = state_dict.pop("@lookahead_slow", None)
        if slow is not None:
            by_name = {getattr(p, "name", None): p for p in self._params()}
            for name, v in slow.items():
                p = by_name.get(name)
                if p is not None:
                    self._slow[id(p)] = jnp.asarray(np.asarray(v),
                                                    jnp.float32)
        self.inner_optimizer.set_state_dict(state_dict)
