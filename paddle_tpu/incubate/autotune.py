"""Runtime autotune switches (reference: python/paddle/incubate/autotune.py
set_config — three tunables: "kernel" algorithm search, "layout"
NCHW/NHWC flipping, "dataloader" worker-count tuning).

TPU-native mapping:
- kernel: XLA's own autotuner always runs at compile time; the switch is
  recorded and surfaced via get_config (nothing to toggle).
- layout: XLA chooses layouts during compilation; recorded likewise.
- dataloader: APPLIED — when enabled, DataLoaders created with the default
  num_workers=0 get a tuned worker count (bounded by cpu count) so host
  input pipelines overlap device steps, the same effect the reference's
  tuner converges to.
"""
from __future__ import annotations

import json
import os
from typing import Optional

_config = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False, "tuning_steps": 500},
}
_tuned_workers: Optional[int] = None


def set_config(config=None):
    """reference: incubate/autotune.py set_config(config=None). ``config``
    is a dict or a path to a JSON file; None enables everything."""
    global _tuned_workers
    if config is None:
        for sub in _config.values():
            sub["enable"] = True
    else:
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        # validate BEFORE mutating: a bad key must not leave the config
        # half-applied
        for key in config:
            if key not in _config:
                raise ValueError(f"unknown autotune section {key!r} "
                                 f"(one of {list(_config)})")
        for key, val in config.items():
            _config[key].update(val)
    if _config["dataloader"]["enable"]:
        _tuned_workers = max(1, min(4, (os.cpu_count() or 2) // 2))
    else:
        _tuned_workers = None


def get_config():
    return json.loads(json.dumps(_config))  # deep copy


def tuned_num_workers() -> Optional[int]:
    """DataLoader consults this when constructed with num_workers=0."""
    return _tuned_workers
