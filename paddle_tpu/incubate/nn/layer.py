"""Fused transformer layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention:24, FusedFeedForward,
FusedTransformerEncoderLayer). Thin Layer wrappers over the functional
fused ops; XLA performs the fusion the reference's CUDA kernels hand-code.
"""
from __future__ import annotations

import math

from ...nn.layer.layers import Layer
from ...nn.initializer.initializer import Constant
from ... import ops
from . import functional as F


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim])
        self.qkv_bias = self.create_parameter(
            [3 * num_heads * self.head_dim], is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim])
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._act = activation
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                  else act_dropout_rate)
        self._epsilon = epsilon
        self._normalize_before = normalize_before
        self.linear1_weight = self.create_parameter([d_model,
                                                     dim_feedforward])
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter([dim_feedforward,
                                                     d_model])
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            self.linear1_bias, self.linear2_bias,
            self.ln1_scale, self.ln1_bias, self.ln2_scale, self.ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate,
            activation=self._act, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon,
            pre_layer_norm=self._normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False, **kw):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedLinear(Layer):
    """reference: incubate/nn/layer/fused_linear.py:26 — Linear backed by
    the fused matmul+bias op."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.fused_matmul_bias(x, self.weight, self.bias,
                                   transpose_y=self._transpose)


class FusedDropoutAdd(Layer):
    """reference: incubate/nn/layer/fused_dropout_add.py:26 —
    y = dropout(x) + residual in one fused op."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p, training=self.training,
                                   mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: incubate/nn/layer/fused_transformer.py:94 —
    out = LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedMultiTransformer(Layer):
    """reference: incubate/nn/layer/fused_transformer.py:1071 — the
    serving transformer stack as ONE layer holding per-layer param lists,
    forwarding through functional.fused_multi_transformer (static KV
    caches, prefill/decode via time_step)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, residual_alpha=1.0,
                 num_layers=-1, nranks=1, trans_qkvw=True, ring_id=-1,
                 norm_type="layernorm", use_neox_rotary_style=False,
                 gqa_group_size=-1, name=None):
        super().__init__()
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if qkv_weight_attrs else 1
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self._epsilon = epsilon
        self._residual_alpha = residual_alpha
        self.normalize_before = normalize_before
        self.activation = activation
        self._trans_qkvw = trans_qkvw
        self._norm_type = norm_type
        self._neox = use_neox_rotary_style
        hd = embed_dim // num_heads
        mk, mkb = self.create_parameter, \
            lambda s: self.create_parameter(s, is_bias=True)
        one = Constant(1.0)
        self.ln_scales = [mk([embed_dim], default_initializer=one)
                          for _ in range(num_layers)]
        self.ln_biases = [mkb([embed_dim]) for _ in range(num_layers)]
        self.qkv_weights = [mk([3, num_heads, hd, embed_dim])
                            for _ in range(num_layers)]
        self.qkv_biases = [mkb([3 * num_heads * hd])
                           for _ in range(num_layers)]
        self.linear_weights = [mk([embed_dim, embed_dim])
                               for _ in range(num_layers)]
        self.linear_biases = [mkb([embed_dim]) for _ in range(num_layers)]
        self.ffn_ln_scales = [mk([embed_dim], default_initializer=one)
                              for _ in range(num_layers)]
        self.ffn_ln_biases = [mkb([embed_dim]) for _ in range(num_layers)]
        self.ffn1_weights = [mk([embed_dim, dim_feedforward])
                             for _ in range(num_layers)]
        self.ffn1_biases = [mkb([dim_feedforward])
                            for _ in range(num_layers)]
        self.ffn2_weights = [mk([dim_feedforward, embed_dim])
                             for _ in range(num_layers)]
        self.ffn2_biases = [mkb([embed_dim]) for _ in range(num_layers)]
        # register list params under stable names
        for attr in ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                     "linear_weights", "linear_biases", "ffn_ln_scales",
                     "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                     "ffn2_weights", "ffn2_biases"):
            for i, pp in enumerate(getattr(self, attr)):
                self.add_parameter(f"{attr}_{i}", pp)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        return F.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self._epsilon,
            residual_alpha=self._residual_alpha, cache_kvs=caches,
            pre_caches=pre_caches, seq_lens=seq_lens,
            rotary_embs=rotary_embs, time_step=time_step,
            attn_mask=attn_mask, rotary_emb_dims=rotary_emb_dims,
            activation=self.activation, training=self.training,
            trans_qkvw=self._trans_qkvw, norm_type=self._norm_type,
            use_neox_rotary_style=self._neox)


class FusedDropout(Layer):
    """reference: incubate/nn/layer/fused_dropout_nd.py:20 — dropout with
    an axis argument (shared mask along the non-listed axes)."""

    def __init__(self, p=0.5, axis=None, mode="upscale_in_train",
                 name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        from ...nn.functional.common import dropout
        return dropout(x, p=self.p, axis=self.axis,
                       training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, axis={self.axis}, mode={self.mode}"


class FusedTransformer(Layer):
    """reference: incubate/nn/layer/fused_transformer.py:951 — the full
    encoder-decoder container. The reference's own forward raises
    NotImplementedError (fused_transformer.py:1065); this mirrors that
    contract, existing for construction/config parity."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        raise NotImplementedError(
            "FusedTransformer.forward is unimplemented in the reference "
            "too (fused_transformer.py:1065); compose "
            "FusedTransformerEncoderLayer / FusedMultiHeadAttention "
            "directly")
