"""Fused-op functional APIs (reference: python/paddle/incubate/nn/functional/
— fused_transformer.py, fused_rms_norm.py, swiglu.py, fused_rotary_position_
embedding.py, fused_bias_act, fused_dropout_add, masked_multihead_attention,
fused_moe; CUDA kernels paddle/phi/kernels/fusion/*).

TPU-native: each is a jnp composition designed so XLA fuses it into one or
few kernels (elementwise chains fold into neighbouring matmuls on the MXU);
on TPU the hot three (fused_rms_norm, swiglu, fused_rotary_position_
embedding) dispatch to the hand-written Pallas kernels in
``ops/pallas/fused.py`` when the call matches the kernels' fully-fused
contract; attention routes to the Pallas flash kernel where applicable.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp

from ...._core.autograd import apply
from ...._core.tensor import Tensor
from ....ops._registry import as_tensor


def _use_pallas_fused() -> bool:
    """Dispatch to the Pallas fused kernels: on TPU by default (these
    APIs' contract IS the fused kernel); elsewhere only when forced
    (interpret mode is correct but slow — tests use the env).

    ``PADDLE_TPU_FORCE_PALLAS_FUSED=1`` forces the kernels anywhere;
    ``=0`` opts out everywhere (fall back to the XLA-fused jnp
    composition, e.g. after a bench shows it faster on a given shape).

    Device PLATFORM, not backend name: the axon PJRT tunnel registers a
    backend called "axon" whose devices are real TPU chips (same check as
    ops/pallas/flash_attention.available)."""
    force = os.environ.get("PADDLE_TPU_FORCE_PALLAS_FUSED")
    if force == "1":
        return True
    if force == "0":
        return False
    from ....ops.pallas import flash_attention as _fa
    return _fa.available()


__all__ = [
    "fused_rms_norm", "fused_layer_norm", "swiglu",
    "fused_rotary_position_embedding", "fused_bias_act",
    "fused_dropout_add", "fused_linear", "fused_linear_activation",
    "fused_matmul_bias", "fused_feedforward", "fused_multi_head_attention",
    "fused_bias_dropout_residual_layer_norm", "masked_multihead_attention",
    "fused_moe",
    "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
]


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **_):
    """reference: incubate/nn/functional/fused_rms_norm.py — rms norm with
    optional pre-norm bias/residual add. Returns (out, residual_out) like
    the reference when residual is given, else out."""
    x = as_tensor(x)
    args = [x]
    opt = {}
    for nm, t in (("bias", bias), ("residual", residual),
                  ("w", norm_weight), ("b", norm_bias)):
        if t is not None:
            opt[nm] = len(args)
            args.append(as_tensor(t))
    ax = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    naxes = tuple(range(ax, x.ndim))

    # fully-fused Pallas path (fused_rms_norm.py's hot shape: norm over the
    # last axis with a weight, no biases)
    if (_use_pallas_fused() and norm_bias is None and bias is None
            and norm_weight is not None and ax == x.ndim - 1):
        from ....ops.pallas import fused as _pf

        if residual is not None:
            def fp(v, res, w):
                return _pf.rms_norm(v, w, float(epsilon), residual=res)
            return apply(fp, x, as_tensor(residual), as_tensor(norm_weight),
                         name="fused_rms_norm", multi_out=True)

        def fp(v, w):
            return _pf.rms_norm(v, w, float(epsilon))
        return apply(fp, x, as_tensor(norm_weight), name="fused_rms_norm")

    def f(v, *rest):
        ct = jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) else v.dtype
        vv = v.astype(ct)
        if "bias" in opt:
            vv = vv + rest[opt["bias"] - 1].astype(ct)
        if "residual" in opt:
            vv = vv + rest[opt["residual"] - 1].astype(ct)
        res_out = vv
        var = jnp.mean(jnp.square(vv), axis=naxes, keepdims=True)
        out = vv * jax.lax.rsqrt(var + epsilon)
        if "w" in opt:
            out = out * rest[opt["w"] - 1].astype(ct)
        if "b" in opt:
            out = out + rest[opt["b"] - 1].astype(ct)
        if "residual" in opt:
            return out.astype(v.dtype), res_out.astype(v.dtype)
        return out.astype(v.dtype)

    if residual is not None:
        return apply(f, *args, name="fused_rms_norm", multi_out=True)
    return apply(f, *args, name="fused_rms_norm")


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **_):
    """reference: incubate/nn/functional/fused_layer_norm.py."""
    x = as_tensor(x)
    args = [x]
    opt = {}
    for nm, t in (("bias", bias), ("residual", residual),
                  ("w", norm_weight), ("b", norm_bias)):
        if t is not None:
            opt[nm] = len(args)
            args.append(as_tensor(t))
    ax = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    naxes = tuple(range(ax, x.ndim))

    def f(v, *rest):
        ct = jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) else v.dtype
        vv = v.astype(ct)
        if "bias" in opt:
            vv = vv + rest[opt["bias"] - 1].astype(ct)
        if "residual" in opt:
            vv = vv + rest[opt["residual"] - 1].astype(ct)
        res_out = vv
        mean = jnp.mean(vv, axis=naxes, keepdims=True)
        var = jnp.mean(jnp.square(vv - mean), axis=naxes, keepdims=True)
        out = (vv - mean) * jax.lax.rsqrt(var + epsilon)
        if "w" in opt:
            out = out * rest[opt["w"] - 1].astype(ct)
        if "b" in opt:
            out = out + rest[opt["b"] - 1].astype(ct)
        if "residual" in opt:
            return out.astype(v.dtype), res_out.astype(v.dtype)
        return out.astype(v.dtype)

    if residual is not None:
        return apply(f, *args, name="fused_layer_norm", multi_out=True)
    return apply(f, *args, name="fused_layer_norm")


def swiglu(x, y=None, name=None):
    """reference: incubate/nn/functional/swiglu.py — silu(x) * y; if y is
    None, x is split in half along the last dim. On TPU the two-operand
    form runs the one-pass Pallas kernel (fused_bias_act swiglu path)."""
    x = as_tensor(x)
    if y is None:
        if _use_pallas_fused():
            from ....ops.pallas import fused as _pf

            def fsplit(v):
                a, b = jnp.split(v, 2, axis=-1)
                return _pf.swiglu(a, b)
            return apply(fsplit, x, name="swiglu")

        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a.astype(jnp.float32)).astype(v.dtype) * b
        return apply(f, x, name="swiglu")
    y = as_tensor(y)
    if _use_pallas_fused():
        from ....ops.pallas import fused as _pf
        return apply(lambda a, b: _pf.swiglu(a, b), x, y, name="swiglu")
    return apply(
        lambda a, b: jax.nn.silu(a.astype(jnp.float32)).astype(a.dtype) * b,
        x, y, name="swiglu")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    rotary_emb_base=10000.0, time_major=False):
    """reference: incubate/nn/functional/fused_rotary_position_embedding.py
    (kernel paddle/phi/kernels/fusion/fused_rope_kernel.cu). q/k/v:
    (B, S, H, D). Returns rotated (q, k, v) (None passthrough)."""
    outs = []
    tensors = [t for t in (q, k, v) if t is not None]
    q0 = as_tensor(tensors[0])
    B, S, H, D = q0.shape
    if sin is None or cos is None:
        inv = 1.0 / (rotary_emb_base **
                     (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        t = jnp.arange(S, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        cos_t, sin_t = jnp.cos(freqs), jnp.sin(freqs)
    else:
        cos_t = as_tensor(cos)._value.reshape(S, -1)[:, :D // 2]
        sin_t = as_tensor(sin)._value.reshape(S, -1)[:, :D // 2]
    if position_ids is not None:
        pid = as_tensor(position_ids)._value  # (B, S)
        cos_t = jnp.take(cos_t, pid, axis=0)  # (B, S, D/2)
        sin_t = jnp.take(sin_t, pid, axis=0)
        expand = lambda c: c[:, :, None, :]
    else:
        expand = lambda c: c[None, :, None, :]

    # fully-fused Pallas path (fused_rope_kernel.cu's hot shape: neox
    # style, shared tables, q+k in one launch)
    if (_use_pallas_fused() and use_neox_rotary_style
            and position_ids is None and q is not None and k is not None
            and v is None):
        from ....ops.pallas import fused as _pf

        def frope(qv, kv):
            return _pf.rope_qk(qv, kv, cos_t, sin_t)   # (S, D/2) tables
        rq, rk = apply(frope, as_tensor(q), as_tensor(k),
                       name="fused_rope", multi_out=True)
        return rq, rk, None

    def rot(t):
        def f(x):
            c = expand(cos_t).astype(jnp.float32)
            s = expand(sin_t).astype(jnp.float32)
            xf = x.astype(jnp.float32)
            if use_neox_rotary_style:
                x1, x2 = jnp.split(xf, 2, axis=-1)
                out = jnp.concatenate(
                    [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
            else:  # GPT-J interleaved pairs
                x1 = xf[..., 0::2]
                x2 = xf[..., 1::2]
                o1 = x1 * c - x2 * s
                o2 = x2 * c + x1 * s
                out = jnp.stack([o1, o2], axis=-1).reshape(xf.shape)
            return out.astype(x.dtype)
        return apply(f, as_tensor(t), name="fused_rope")

    result = tuple(rot(t) if t is not None else None for t in (q, k, v))
    return result


_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x.astype(jnp.float32)).astype(x.dtype),
    "relu": jax.nn.relu,
    "silu": lambda x: jax.nn.silu(x.astype(jnp.float32)).astype(x.dtype),
    "swiglu": None,  # handled specially
    "geglu": None,
}


def fused_bias_act(x, bias=None, act_method="gelu", **_):
    """reference: incubate/nn/functional/fused_bias_act (kernel
    fused_bias_act_kernel.cu): out = act(x + bias), with swiglu/geglu
    splitting the last dim."""
    x = as_tensor(x)
    args = [x]
    if bias is not None:
        args.append(as_tensor(bias))

    def f(v, *rest):
        if rest:
            v = v + rest[0]
        if act_method in ("swiglu", "geglu"):
            a, b = jnp.split(v, 2, axis=-1)
            g = (jax.nn.silu if act_method == "swiglu" else jax.nn.gelu)(
                a.astype(jnp.float32)).astype(v.dtype)
            return g * b
        return _ACTS[act_method](v)
    return apply(f, *args, name="fused_bias_act")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """reference: incubate/nn/functional/fused_dropout_add.py —
    dropout(x) + y in one pass."""
    from ....nn.functional.common import dropout
    d = dropout(x, p=p, training=training, mode=mode)
    return d + as_tensor(y)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """reference: incubate/nn/functional/blha etc. fused_matmul_bias —
    cublasLt epilogue fusion; XLA does the same fusion natively."""
    x, y = as_tensor(x), as_tensor(y)
    args = [x, y]
    if bias is not None:
        args.append(as_tensor(bias))

    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    return apply(f, *args, name="fused_matmul_bias")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    if activation in (None, "none"):
        return out
    return apply(_ACTS[activation], out, name=f"fused_linear_{activation}")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode='upscale_in_train',
                      name=None):
    """reference: incubate/nn/functional/fused_transformer.py
    fused_feedforward (kernel fused_feedforward_kernel.cu):
    residual + dropout(linear2(dropout(act(linear1(ln(x)))))) with pre/post
    layernorm."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    x = as_tensor(x)
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, d, ln1_scale, ln1_bias, ln1_epsilon)
    h = fused_matmul_bias(x, linear1_weight, linear1_bias)
    h = apply(_ACTS.get(activation, jax.nn.relu), h, name=activation)
    h = dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = layer_norm(out, d, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode='upscale_in_train',
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """reference: fused_transformer.py fused_multi_head_attention (kernel
    fused_attention_kernel.cu). qkv_weight: (3, H, D_head, D_in) as in the
    reference layout."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    from ....nn.functional.attention import scaled_dot_product_attention
    x = as_tensor(x)
    residual = x
    B, S, D = x.shape
    if pre_layer_norm:
        x = layer_norm(x, D, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkvw = as_tensor(qkv_weight)
    three, H, Dh, Din = qkvw.shape
    qkv = fused_matmul_bias(
        x, qkvw.reshape([3 * H * Dh, Din]), qkv_bias, transpose_y=True)
    qkv = qkv.reshape([B, S, 3, H, Dh])

    def split3(t):
        return (apply(lambda v: v[:, :, 0], t, name="slice_q"),
                apply(lambda v: v[:, :, 1], t, name="slice_k"),
                apply(lambda v: v[:, :, 2], t, name="slice_v"))
    q, k, v = split3(qkv)
    o = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate
        if training else 0.0, is_causal=False)
    o = o.reshape([B, S, H * Dh])
    out = fused_matmul_bias(o, linear_weight, linear_bias)
    out = dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = layer_norm(out, D, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode='upscale_in_train',
                                           name=None):
    """reference: incubate/nn/functional/fused_transformer.py."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    x = as_tensor(x)
    if bias is not None:
        x = x + as_tensor(bias)
    x = dropout(x, p=dropout_rate, training=training, mode=mode)
    out = x + as_tensor(residual)
    return layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               out_shift=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False, **_):
    """Decode-time single-token attention against a KV cache
    (reference: incubate/nn/functional/masked_multihead_attention.py,
    kernel masked_multihead_attention_kernel.cu).

    x: (B, 3*H*D) fused qkv for ONE step; cache_kv: (2, B, H, max_seq, D).
    Returns (out (B, H*D), updated cache_kv) following the reference.
    """
    x = as_tensor(x)
    cache = as_tensor(cache_kv)
    args = [x, cache]
    if bias is not None:
        args.append(as_tensor(bias))
    if sequence_lengths is not None:
        args.append(as_tensor(sequence_lengths))

    two, B, H, MS, D = cache.shape

    def f(xv, cachev, *rest):
        i = 0
        if bias is not None:
            xv = xv + rest[i]; i += 1
        if sequence_lengths is not None:
            cur = rest[i].reshape(-1)  # (B,) current lengths
        else:
            cur = None
        qkv = xv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # (B, H, D)
        if cur is None:
            # without explicit lengths, append at position 0 of empty cache
            step = jnp.zeros((B,), jnp.int32)
        else:
            step = cur.astype(jnp.int32)
        bidx = jnp.arange(B)
        ck = cachev[0].at[bidx, :, step].set(k)
        cv = cachev[1].at[bidx, :, step].set(v)
        # attention over cached positions <= step
        s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) / math.sqrt(D)
        pos = jnp.arange(MS)[None, None, :]
        s = jnp.where(pos <= step[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhs,bhsd->bhd", p.astype(cv.dtype), cv)
        return o.reshape(B, H * D), jnp.stack([ck, cv])

    return apply(f, *args, name="masked_multihead_attention", multi_out=True)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2,
              norm_topk_prob=True, **_):
    """reference: incubate/nn/functional/fused_moe.py — top-k routed expert
    FFN. ffn1_weight: (E, H, 2*I) swiglu-packed; ffn2: (E, I, H)."""
    from ....models.moe import MoEConfig, moe_ffn
    x = as_tensor(x)
    gw = as_tensor(gate_weight)
    w1 = as_tensor(ffn1_weight)
    w2 = as_tensor(ffn2_weight)
    E = gw.shape[-1]
    cfg = MoEConfig(num_experts=E, top_k=moe_topk, capacity_factor=4.0)

    def f(xv, gv, w1v, w2v):
        half = w1v.shape[-1] // 2
        params = {"w_gate": gv, "wg": w1v[..., :half],
                  "wu": w1v[..., half:], "wd": w2v}
        squeeze = xv.ndim == 2
        if squeeze:
            xv = xv[None]
        out, _ = moe_ffn(xv, params, cfg)
        return out[0] if squeeze else out
    return apply(f, x, gw, w1, w2, name="fused_moe")


def softmax_mask_fuse(x, mask, name=None):
    """Fused additive-mask softmax (reference:
    paddle/phi/kernels/fusion/gpu/fused_softmax_mask_kernel.cu;
    incubate/nn/functional/fused_softmax_mask.py). x (B, H, S, S) scores,
    mask (B, 1, S, S) additive (-inf style); softmax computed in fp32 —
    XLA fuses the add into the softmax."""
    def fn(xv, mv):
        s32 = xv.astype(jnp.float32) + mv.astype(jnp.float32)
        return jax.nn.softmax(s32, axis=-1).astype(xv.dtype)
    return apply(fn, as_tensor(x), as_tensor(mask),
                 name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal (upper-triangle-masked) softmax (reference:
    fused_softmax_mask_upper_triangle_kernel.cu)."""
    def fn(xv):
        S = xv.shape[-1]
        causal = jnp.tril(jnp.ones((S, S), bool))
        s32 = jnp.where(causal, xv.astype(jnp.float32),
                        jnp.finfo(jnp.float32).min)
        return jax.nn.softmax(s32, axis=-1).astype(xv.dtype)
    return apply(fn, as_tensor(x), name="softmax_mask_fuse_upper_triangle")


# --------------------------------------------------------------------------
# Serving-stack fused ops
# --------------------------------------------------------------------------
def _norm(x, scale, bias, eps, norm_type="layernorm"):
    xv = x.astype(jnp.float32)
    if norm_type == "rmsnorm":
        out = xv * jax.lax.rsqrt(
            jnp.mean(xv * xv, axis=-1, keepdims=True) + eps)
    else:
        mu = jnp.mean(xv, axis=-1, keepdims=True)
        var = jnp.var(xv, axis=-1, keepdims=True)
        out = (xv - mu) * jax.lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, residual_alpha=1.0, cache_kvs=None, beam_offset=None,
        pre_caches=None, seq_lens=None, rotary_embs=None, time_step=None,
        attn_mask=None, dropout_rate=0.0, rotary_emb_dims=0,
        activation="gelu", training=False, mode="upscale_in_train",
        trans_qkvw=True, ring_id=-1, norm_type="layernorm",
        use_neox_rotary_style=False, gqa_group_size=-1, name=None):
    """reference: incubate/nn/functional/fused_transformer.py:976
    fused_multi_transformer / fused_multi_transformer_kernel.cu — the
    whole serving transformer stack in one call, with static KV caches.

    TPU-native: one jnp composition per layer; XLA fuses the LN/bias/act
    chains into the matmuls. ``cache_kvs[i]``: [2, B, nh, max_seq, hd].
    ``time_step`` (int/scalar) = decode position; None = context encode.
    Returns (out, cache_kvs) when caches are given, else out.
    """
    xv = as_tensor(x)._value
    B, S, E = xv.shape
    L = len(qkv_weights)

    def raw(t):
        return None if t is None else as_tensor(t)._value

    def pick(seq, i):
        if seq is None:
            return None
        v = seq[i] if i < len(seq) else None
        return None if v is None else as_tensor(v)._value

    # exact (erf) gelu — the reference kernel's GeluFunctor, not the
    # tanh approximation
    exact_gelu = lambda t: jax.nn.gelu(t, approximate=False)
    act = {"gelu": exact_gelu, "relu": jax.nn.relu,
           "swiglu": None}.get(activation, exact_gelu)
    step = None if time_step is None else int(
        np.asarray(raw(time_step)).reshape(-1)[0]) if not isinstance(
        time_step, int) else time_step
    new_caches = []
    h = xv
    for i in range(L):
        qkvw = raw(qkv_weights[i])
        residual = h
        z = _norm(h, pick(ln_scales, i), pick(ln_biases, i), epsilon,
                  norm_type) if pre_layer_norm else h
        if qkvw.ndim != 4:
            raise ValueError(
                "fused_multi_transformer: qkv_weights must be 4-D — "
                "[3, nh, hd, E] with trans_qkvw=True (default) or "
                "[E, 3, nh, hd] with trans_qkvw=False (a 2-D [E, 3E] "
                "weight cannot encode the head split)")
        if trans_qkvw:           # [3, nh, hd, E]
            three, nh, hd, _ = qkvw.shape
            qkv = z @ qkvw.reshape(3 * nh * hd, E).T.astype(z.dtype)
        else:                    # [E, 3, nh, hd]
            _, three, nh, hd = qkvw.shape
            qkv = z @ qkvw.reshape(E, 3 * nh * hd).astype(z.dtype)
        b = pick(qkv_biases, i)
        if b is not None:
            qkv = qkv + b.reshape(-1).astype(qkv.dtype)
        qkv = qkv.reshape(B, S, 3, nh, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if rotary_embs is not None and rotary_emb_dims > 0:
            rot = raw(rotary_embs)      # [2, B, 1, max_seq, hd]
            pos0 = 0 if step is None else step
            cos = jax.lax.dynamic_slice_in_dim(rot[0], pos0, S, axis=2)
            sin = jax.lax.dynamic_slice_in_dim(rot[1], pos0, S, axis=2)
            cos = jnp.moveaxis(cos, 2, 1)   # [B, S, 1, hd]
            sin = jnp.moveaxis(sin, 2, 1)

            def rope(t):
                if use_neox_rotary_style:
                    h1, h2 = jnp.split(t, 2, axis=-1)
                    rot = jnp.concatenate([-h2, h1], axis=-1)
                else:            # interleaved (GPT-J) pairs — the default
                    te, to = t[..., 0::2], t[..., 1::2]
                    rot = jnp.stack([-to, te], axis=-1).reshape(t.shape)
                return t * cos.astype(t.dtype) + rot * sin.astype(t.dtype)
            q, k = rope(q), rope(k)
        if cache_kvs is not None:
            cache = raw(cache_kvs[i])    # [2, B, nh, max_seq, hd]
            kt = jnp.moveaxis(k, 1, 2)   # [B, nh, S, hd]
            vt = jnp.moveaxis(v, 1, 2)
            pos = 0 if step is None else step
            ck = jax.lax.dynamic_update_slice_in_dim(cache[0], kt.astype(
                cache.dtype), pos, axis=2)
            cv = jax.lax.dynamic_update_slice_in_dim(cache[1], vt.astype(
                cache.dtype), pos, axis=2)
            new_caches.append(Tensor(jnp.stack([ck, cv]), _internal=True))
            kk, vv = ck, cv
            logits = jnp.einsum("bqhd,bhkd->bhqk",
                                q.astype(jnp.float32),
                                kk.astype(jnp.float32)) / math.sqrt(hd)
            kpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)
            qpos = pos + jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                                  2)
            logits = jnp.where(kpos <= qpos, logits, -1e30)
            if attn_mask is not None:
                # same contract as the no-cache branch: bool keeps, float
                # adds; broadcast over [B, 1|nh, Sq, cache_len]
                m = raw(attn_mask)
                mw = m[..., :logits.shape[-1]]
                if m.dtype == jnp.bool_:
                    logits = jnp.where(mw, logits, -1e30)
                else:
                    logits = logits + mw.astype(jnp.float32)
            p = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhqk,bhkd->bqhd", p.astype(vv.dtype), vv)
        else:
            logits = jnp.einsum("bqhd,bkhd->bhqk",
                                q.astype(jnp.float32),
                                k.astype(jnp.float32)) / math.sqrt(hd)
            if attn_mask is not None:
                m = raw(attn_mask)
                if m.dtype == jnp.bool_:
                    logits = jnp.where(m, logits, -1e30)
                else:
                    logits = logits + m.astype(jnp.float32)
            else:
                kpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)
                qpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
                logits = jnp.where(kpos <= qpos, logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
        o = o.reshape(B, S, nh * hd)
        lw = raw(linear_weights[i])
        o = o @ lw.astype(o.dtype)
        lb = pick(linear_biases, i)
        if lb is not None:
            o = o + lb.astype(o.dtype)
        h = residual * residual_alpha + o
        if not pre_layer_norm:
            h = _norm(h, pick(ln_scales, i), pick(ln_biases, i), epsilon,
                      norm_type)
        # ffn
        residual = h
        z = _norm(h, pick(ffn_ln_scales, i), pick(ffn_ln_biases, i),
                  epsilon, norm_type) if pre_layer_norm else h
        f1 = z @ raw(ffn1_weights[i]).astype(z.dtype)
        f1b = pick(ffn1_biases, i)
        if f1b is not None:
            f1 = f1 + f1b.astype(f1.dtype)
        if activation == "swiglu":
            g, u = jnp.split(f1, 2, axis=-1)
            f1 = jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u
        else:
            f1 = act(f1.astype(jnp.float32)).astype(f1.dtype)
        f2 = f1 @ raw(ffn2_weights[i]).astype(f1.dtype)
        f2b = pick(ffn2_biases, i)
        if f2b is not None:
            f2 = f2 + f2b.astype(f2.dtype)
        h = residual * residual_alpha + f2
        if not pre_layer_norm:
            h = _norm(h, pick(ffn_ln_scales, i), pick(ffn_ln_biases, i),
                      epsilon, norm_type)
    out = Tensor(h, _internal=True)
    return (out, new_caches) if cache_kvs is not None else out


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size=None,
                     name=None):
    """reference: incubate/nn/functional/blha_get_max_len.py — max
    encoder/decoder lengths for block attention planning."""
    enc = as_tensor(seq_lens_encoder)._value
    dec = as_tensor(seq_lens_decoder)._value
    return (Tensor(jnp.max(enc).reshape(1), _internal=True),
            Tensor(jnp.max(dec).reshape(1), _internal=True))


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets=None, cum_offsets=None,
        cu_seqlens_q=None, cu_seqlens_k=None, block_tables=None,
        pre_key_cache=None, pre_value_cache=None,
        cache_k_quant_scales=None, cache_v_quant_scales=None,
        cache_k_dequant_scales=None, cache_v_dequant_scales=None,
        rope_emb=None, mask=None,
        tgt_mask=None, max_seq_len=-1, block_size=64, use_neox_style=False,
        qkv_bias=None, out_shift=None, out_smooth=None,
        max_enc_len_this_time=None, max_dec_len_this_time=None,
        use_dynamic_cachekv_quant=False, **_):
    """reference: incubate/nn/functional/block_multihead_attention.py /
    block_multi_head_attention_kernel.cu — PAGED-kv-cache attention: each
    sequence's cache lives in `block_size`-row pages addressed through
    ``block_tables`` (vLLM-style), mixing prefill rows and decode rows in
    one varlen token batch.

    TPU-native correctness path (jnp; the Pallas decode kernel covers the
    contiguous-cache hot loop): per-row gather of the page list ->
    contiguous K/V -> masked attention. Shapes:
      qkv            [total_tokens, 3*nh*hd]
      key/value_cache[num_blocks, nh, block_size, hd]
      block_tables   [B, max_blocks_per_seq] (-1 padded)
    Returns (out [total_tokens, nh*hd], qkv, key_cache, value_cache).

    **int8 KV cache** (reference: cache_k/v_quant_scales +
    use_dynamic_cachekv_quant — the cachekv-int8 serving tier): when
    quant scales are given the caches hold int8; writes quantize new
    rows with the per-head (static, [nh]) or per-sequence-per-head
    (dynamic, [B, nh]) quant scales, reads dequantize with the
    dequant scales (default 1/quant). Halves KV HBM, the long-context
    decode bandwidth win.
    """
    if (pre_key_cache is None) != (pre_value_cache is None):
        raise ValueError(
            "block_multihead_attention: pre_key_cache and "
            "pre_value_cache must be passed together")
    # pre caches (reference: block_multihead_attention.py:45,86 —
    # [B, num_head, pre_len, head_dim]): prefix-tuning-style virtual
    # tokens PREPENDED to every sequence's attention context. They are
    # fully visible to all queries, never occupy the paged cache, and do
    # not shift real token positions (rope indices stay 0-based).
    pre_k = (as_tensor(pre_key_cache)._value
             if pre_key_cache is not None else None)
    pre_v = (as_tensor(pre_value_cache)._value
             if pre_value_cache is not None else None)
    qv = as_tensor(qkv)._value
    kc = as_tensor(key_cache)._value
    vc = as_tensor(value_cache)._value
    enc = np.asarray(as_tensor(seq_lens_encoder)._value)
    dec = np.asarray(as_tensor(seq_lens_decoder)._value)
    this = np.asarray(as_tensor(seq_lens_this_time)._value)
    bt = np.asarray(as_tensor(block_tables)._value)
    if qkv_bias is not None:
        qv = qv + as_tensor(qkv_bias)._value.reshape(-1)
    nh, bs, hd = kc.shape[1], kc.shape[2], kc.shape[3]
    B = bt.shape[0]
    total = qv.shape[0]
    q3 = qv.reshape(total, 3, nh, hd)

    kq = (as_tensor(cache_k_quant_scales)._value
          if cache_k_quant_scales is not None else None)
    vq = (as_tensor(cache_v_quant_scales)._value
          if cache_v_quant_scales is not None else None)
    kdq = (as_tensor(cache_k_dequant_scales)._value
           if cache_k_dequant_scales is not None else
           (1.0 / kq if kq is not None else None))
    vdq = (as_tensor(cache_v_dequant_scales)._value
           if cache_v_dequant_scales is not None else
           (1.0 / vq if vq is not None else None))
    if (kq is None) != (vq is None):
        raise ValueError(
            "block_multihead_attention: cache_k_quant_scales and "
            "cache_v_quant_scales must be passed together (got only "
            f"{'k' if kq is not None else 'v'} scales) — an int8 cache "
            "quantizes both K and V")
    cache_quant = kq is not None
    if cache_quant:
        want = 2 if use_dynamic_cachekv_quant else 1
        for nm, s in (("cache_k_quant_scales", kq),
                      ("cache_v_quant_scales", vq)):
            if jnp.ndim(s) != want:
                raise ValueError(
                    f"block_multihead_attention: {nm} must be "
                    f"{'[B, num_head]' if want == 2 else '[num_head]'} "
                    f"for use_dynamic_cachekv_quant="
                    f"{use_dynamic_cachekv_quant}, got ndim "
                    f"{jnp.ndim(s)}")

    def _sc(scales, b, shape):
        """Per-head scale broadcast: static [nh] or dynamic [B, nh]."""
        s = scales[b] if (use_dynamic_cachekv_quant and
                          jnp.ndim(scales) == 2) else scales
        return jnp.asarray(s, jnp.float32).reshape(shape)

    def _quant_rows(x, scales, b):
        # x: (t, nh, hd) new rows -> int8
        s = _sc(scales, b, (1, nh, 1))
        return jnp.clip(jnp.round(x.astype(jnp.float32) * s),
                        -127, 127).astype(jnp.int8)

    def _dequant_ctx(x, scales, b):
        # x: (nh, kl, hd) gathered cache -> fp32
        s = _sc(scales, b, (nh, 1, 1))
        return x.astype(jnp.float32) * s

    # pure-decode batches (one new token per sequence, no prefill rows)
    # take the Pallas paged-attention kernel: the block-table gather rides
    # the kernel's scalar-prefetch index map instead of materializing a
    # contiguous copy per sequence
    from ....ops.pallas import fused as _pf
    if (rope_emb is None and mask is None and total == B
            and int(enc.max(initial=0)) == 0 and np.all(this == 1)
            and pre_k is None
            and _pf.available()):   # True on TPU or under set_interpret
        q1 = q3[:, 0]                       # (B, nh, hd)
        pos = dec.astype(np.int64)
        pages = jnp.asarray(bt[np.arange(B), pos // bs].astype(np.int32))
        rows = jnp.asarray((pos % bs).astype(np.int32))
        if cache_quant:
            # int8 pages stay int8 in HBM; the kernel dequants in VMEM.
            # ONE vectorized quantize per cache — this is the decode hot
            # path, not a place for a per-sequence python loop
            def _qbatch(x, scales):   # x: (B, nh, hd)
                s = jnp.asarray(scales, jnp.float32)
                s = s[:, :, None] if use_dynamic_cachekv_quant \
                    else s.reshape(1, nh, 1)
                return jnp.clip(jnp.round(x.astype(jnp.float32) * s),
                                -127, 127).astype(jnp.int8)
            kc = kc.at[pages, :, rows].set(_qbatch(q3[:, 1], kq))
            vc = vc.at[pages, :, rows].set(_qbatch(q3[:, 2], vq))
        else:
            kc = kc.at[pages, :, rows].set(q3[:, 1].astype(kc.dtype))
            vc = vc.at[pages, :, rows].set(q3[:, 2].astype(vc.dtype))
        # kernel page layout: (P, HK, page, D) == this cache layout
        out = _pf.paged_decode_attention(
            q1, kc, vc, jnp.asarray(bt), jnp.asarray(
                (dec + 1).astype(np.int32)),
            k_dequant_scale=kdq if cache_quant else None,
            v_dequant_scale=vdq if cache_quant else None)
        return (Tensor(out.reshape(B, nh * hd), _internal=True),
                Tensor(qv, _internal=True), Tensor(kc, _internal=True),
                Tensor(vc, _internal=True))

    outs = []
    tok = 0
    for b in range(B):
        t = int(this[b])
        if t == 0:
            continue
        q = q3[tok:tok + t, 0]
        k_new = q3[tok:tok + t, 1]
        v_new = q3[tok:tok + t, 2]
        start = int(dec[b])          # existing cache length (decode rows)
        if int(enc[b]) > 0:
            start = 0                # prefill writes from position 0
        if rope_emb is not None:
            rot = as_tensor(rope_emb)._value   # [2, 1|B, 1, max_seq, hd]
            rb = rot[:, b] if rot.shape[1] > 1 else rot[:, 0]
            cos = rb[0, 0, start:start + t][:, None, :]
            sin = rb[1, 0, start:start + t][:, None, :]

            def rope_t(tn):
                if use_neox_style:
                    h1, h2 = jnp.split(tn, 2, axis=-1)
                    r = jnp.concatenate([-h2, h1], axis=-1)
                else:
                    te, to = tn[..., 0::2], tn[..., 1::2]
                    r = jnp.stack([-to, te], axis=-1).reshape(tn.shape)
                return tn * cos.astype(tn.dtype) + r * sin.astype(tn.dtype)
            q, k_new = rope_t(q), rope_t(k_new)
        # ONE vectorized page scatter for this row's tokens
        pos = start + np.arange(t)
        pages = jnp.asarray(bt[b, pos // bs].astype(np.int32))
        rows = jnp.asarray((pos % bs).astype(np.int32))
        if cache_quant:
            kc = kc.at[pages, :, rows].set(_quant_rows(k_new, kq, b))
            vc = vc.at[pages, :, rows].set(_quant_rows(v_new, vq, b))
        else:
            kc = kc.at[pages, :, rows].set(k_new.astype(kc.dtype))
            vc = vc.at[pages, :, rows].set(v_new.astype(vc.dtype))
        kl = start + t
        npages = (kl + bs - 1) // bs
        pages = [int(bt[b, p]) for p in range(npages)]
        ks = jnp.concatenate([kc[p] for p in pages], axis=1)[:, :kl]
        vs = jnp.concatenate([vc[p] for p in pages], axis=1)[:, :kl]
        if cache_quant:
            ks = _dequant_ctx(ks, kdq, b)
            vs = _dequant_ctx(vs, vdq, b).astype(qv.dtype)
        plen = 0
        if pre_k is not None:
            # prepend the prefix context: columns [0, plen) are virtual
            # tokens visible to every query; cache columns shift right
            plen = pre_k.shape[2]
            ks = jnp.concatenate([pre_k[b].astype(ks.dtype), ks], axis=1)
            vs = jnp.concatenate([pre_v[b].astype(vs.dtype), vs], axis=1)
        logits = jnp.einsum("qhd,hkd->hqk", q.astype(jnp.float32),
                            ks.astype(jnp.float32)) / math.sqrt(hd)
        qpos = start + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        kpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
        logits = jnp.where((kpos < plen) | (kpos - plen <= qpos),
                           logits, -1e30)
        if mask is not None:
            mv = as_tensor(mask)._value    # [B, 1, Smax, Smax]-broadcast
            mb = mv[b if mv.shape[0] > 1 else 0]
            mb = mb[..., start:start + t, :kl].astype(jnp.float32)
            if plen:
                # the user mask addresses real cache positions; prefix
                # columns are additively transparent
                mb = jnp.concatenate(
                    [jnp.zeros(mb.shape[:-1] + (plen,), jnp.float32), mb],
                    axis=-1)
            logits = logits + mb
        p = jax.nn.softmax(logits, axis=-1)
        o = jnp.einsum("hqk,hkd->qhd", p.astype(vs.dtype), vs)
        outs.append(o.reshape(t, nh * hd))
        tok += t
    out = jnp.concatenate(outs, axis=0) if outs else \
        jnp.zeros((0, nh * hd), qv.dtype)
    return (Tensor(out, _internal=True), Tensor(qv, _internal=True),
            Tensor(kc, _internal=True), Tensor(vc, _internal=True))


def fused_dot_product_attention(q, k, v, attn_mask=None, scaling_factor=None,
                                dropout_p=0.0, is_causal=False,
                                training=False, name=None, **_):
    """reference: incubate/nn/functional/fused_dot_product_attention.py —
    cuDNN fused SDPA; here the flash/sdpa path (Pallas on TPU)."""
    from ....nn.functional.attention import scaled_dot_product_attention
    return scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
        is_causal=is_causal, training=training)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0, name=None):
    """reference: incubate/nn/functional/
    variable_length_memory_efficient_attention.py — varlen attention with
    per-sequence lengths. q/k/v: [B, nh, S, hd]; seq_lens [B, 1]."""
    qv = as_tensor(query)._value
    kv = as_tensor(key)._value
    vv = as_tensor(value)._value
    ql = as_tensor(seq_lens)._value.reshape(-1)
    kl = as_tensor(kv_seq_lens)._value.reshape(-1)
    hd = qv.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qv.astype(jnp.float32),
                        kv.astype(jnp.float32)) * sc
    qpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
    kpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)
    valid = (qpos < ql[:, None, None, None]) & \
        (kpos < kl[:, None, None, None])
    if causal:
        valid = valid & (kpos <= qpos)
    if mask is not None:
        m = as_tensor(mask)._value
        logits = logits + m.astype(jnp.float32)
    logits = jnp.where(valid, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    p = jnp.where(valid, p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vv.dtype), vv)
    return Tensor(out, _internal=True)


def fused_gate_attention(query, key=None, query_weight=None,
                         key_weight=None, value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False, name=None):
    """reference: incubate/nn/functional fused_gate_attention
    (AlphaFold-style gated attention, fused_gate_attention_kernel).
    query: [B, M, S, E]; qkv_weight: [3, nh, hd, E] when merge_qkv."""
    qv = as_tensor(query)._value

    def raw(t):
        return None if t is None else as_tensor(t)._value
    if merge_qkv:
        w = raw(qkv_weight)          # [3, nh, hd, E]
        three, nh, hd, E = w.shape
        qkv = jnp.einsum("bmse,cnde->bmscnd", qv, w)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
    else:
        kv = as_tensor(key)._value
        qw, kw, vw = raw(query_weight), raw(key_weight), raw(value_weight)
        # per-projection weights: [E, nh, hd]
        q = jnp.einsum("bmse,end->bmsnd", qv, qw)
        k = jnp.einsum("bmse,end->bmsnd", kv, kw)
        v = jnp.einsum("bmse,end->bmsnd", kv, vw)
    logits = jnp.einsum("bmsnd,bmtnd->bmnst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(q.shape[-1])
    if nonbatched_bias is not None:
        logits = logits + raw(nonbatched_bias).astype(jnp.float32)[:, None]
    if attn_mask is not None:
        m = raw(attn_mask)
        logits = logits + (1.0 - m.astype(jnp.float32)) * -1e9
    p = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bmnst,bmtnd->bmsnd", p.astype(v.dtype), v)
    if has_gating and gate_linear_weight is not None:
        gw = raw(gate_linear_weight)      # [E, nh, hd]
        g = jnp.einsum("bmse,end->bmsnd", qv, gw)
        if gate_linear_bias is not None:
            g = g + raw(gate_linear_bias).astype(g.dtype)
        o = o * jax.nn.sigmoid(g.astype(jnp.float32)).astype(o.dtype)
    ow = raw(out_linear_weight)           # [nh, hd, E]
    out = jnp.einsum("bmsnd,nde->bmse", o, ow)
    if out_linear_bias is not None:
        out = out + raw(out_linear_bias).astype(out.dtype)
    return Tensor(out, _internal=True)


import numpy as np  # noqa: E402 — used by fused_multi_transformer

__all__ += ["fused_multi_transformer", "block_multihead_attention",
            "blha_get_max_len", "fused_dot_product_attention",
            "variable_length_memory_efficient_attention",
            "fused_gate_attention"]
