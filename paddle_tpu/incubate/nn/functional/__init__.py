"""Fused-op functional APIs (reference: python/paddle/incubate/nn/functional/
— fused_transformer.py, fused_rms_norm.py, swiglu.py, fused_rotary_position_
embedding.py, fused_bias_act, fused_dropout_add, masked_multihead_attention,
fused_moe; CUDA kernels paddle/phi/kernels/fusion/*).

TPU-native: each is a jnp composition designed so XLA fuses it into one or
few kernels (elementwise chains fold into neighbouring matmuls on the MXU);
on TPU the hot three (fused_rms_norm, swiglu, fused_rotary_position_
embedding) dispatch to the hand-written Pallas kernels in
``ops/pallas/fused.py`` when the call matches the kernels' fully-fused
contract; attention routes to the Pallas flash kernel where applicable.
"""
from __future__ import annotations

import math
import os

import jax
import jax.numpy as jnp

from ...._core.autograd import apply
from ...._core.tensor import Tensor
from ....ops._registry import as_tensor


def _use_pallas_fused() -> bool:
    """Dispatch to the Pallas fused kernels: on TPU by default (these
    APIs' contract IS the fused kernel); elsewhere only when forced
    (interpret mode is correct but slow — tests use the env).

    ``PADDLE_TPU_FORCE_PALLAS_FUSED=1`` forces the kernels anywhere;
    ``=0`` opts out everywhere (fall back to the XLA-fused jnp
    composition, e.g. after a bench shows it faster on a given shape).

    Device PLATFORM, not backend name: the axon PJRT tunnel registers a
    backend called "axon" whose devices are real TPU chips (same check as
    ops/pallas/flash_attention.available)."""
    force = os.environ.get("PADDLE_TPU_FORCE_PALLAS_FUSED")
    if force == "1":
        return True
    if force == "0":
        return False
    from ....ops.pallas import flash_attention as _fa
    return _fa.available()


__all__ = [
    "fused_rms_norm", "fused_layer_norm", "swiglu",
    "fused_rotary_position_embedding", "fused_bias_act",
    "fused_dropout_add", "fused_linear", "fused_linear_activation",
    "fused_matmul_bias", "fused_feedforward", "fused_multi_head_attention",
    "fused_bias_dropout_residual_layer_norm", "masked_multihead_attention",
    "fused_moe",
    "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
]


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, **_):
    """reference: incubate/nn/functional/fused_rms_norm.py — rms norm with
    optional pre-norm bias/residual add. Returns (out, residual_out) like
    the reference when residual is given, else out."""
    x = as_tensor(x)
    args = [x]
    opt = {}
    for nm, t in (("bias", bias), ("residual", residual),
                  ("w", norm_weight), ("b", norm_bias)):
        if t is not None:
            opt[nm] = len(args)
            args.append(as_tensor(t))
    ax = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    naxes = tuple(range(ax, x.ndim))

    # fully-fused Pallas path (fused_rms_norm.py's hot shape: norm over the
    # last axis with a weight, no biases)
    if (_use_pallas_fused() and norm_bias is None and bias is None
            and norm_weight is not None and ax == x.ndim - 1):
        from ....ops.pallas import fused as _pf

        if residual is not None:
            def fp(v, res, w):
                return _pf.rms_norm(v, w, float(epsilon), residual=res)
            return apply(fp, x, as_tensor(residual), as_tensor(norm_weight),
                         name="fused_rms_norm", multi_out=True)

        def fp(v, w):
            return _pf.rms_norm(v, w, float(epsilon))
        return apply(fp, x, as_tensor(norm_weight), name="fused_rms_norm")

    def f(v, *rest):
        ct = jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) else v.dtype
        vv = v.astype(ct)
        if "bias" in opt:
            vv = vv + rest[opt["bias"] - 1].astype(ct)
        if "residual" in opt:
            vv = vv + rest[opt["residual"] - 1].astype(ct)
        res_out = vv
        var = jnp.mean(jnp.square(vv), axis=naxes, keepdims=True)
        out = vv * jax.lax.rsqrt(var + epsilon)
        if "w" in opt:
            out = out * rest[opt["w"] - 1].astype(ct)
        if "b" in opt:
            out = out + rest[opt["b"] - 1].astype(ct)
        if "residual" in opt:
            return out.astype(v.dtype), res_out.astype(v.dtype)
        return out.astype(v.dtype)

    if residual is not None:
        return apply(f, *args, name="fused_rms_norm", multi_out=True)
    return apply(f, *args, name="fused_rms_norm")


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, **_):
    """reference: incubate/nn/functional/fused_layer_norm.py."""
    x = as_tensor(x)
    args = [x]
    opt = {}
    for nm, t in (("bias", bias), ("residual", residual),
                  ("w", norm_weight), ("b", norm_bias)):
        if t is not None:
            opt[nm] = len(args)
            args.append(as_tensor(t))
    ax = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    naxes = tuple(range(ax, x.ndim))

    def f(v, *rest):
        ct = jnp.float32 if v.dtype in (jnp.bfloat16, jnp.float16) else v.dtype
        vv = v.astype(ct)
        if "bias" in opt:
            vv = vv + rest[opt["bias"] - 1].astype(ct)
        if "residual" in opt:
            vv = vv + rest[opt["residual"] - 1].astype(ct)
        res_out = vv
        mean = jnp.mean(vv, axis=naxes, keepdims=True)
        var = jnp.mean(jnp.square(vv - mean), axis=naxes, keepdims=True)
        out = (vv - mean) * jax.lax.rsqrt(var + epsilon)
        if "w" in opt:
            out = out * rest[opt["w"] - 1].astype(ct)
        if "b" in opt:
            out = out + rest[opt["b"] - 1].astype(ct)
        if "residual" in opt:
            return out.astype(v.dtype), res_out.astype(v.dtype)
        return out.astype(v.dtype)

    if residual is not None:
        return apply(f, *args, name="fused_layer_norm", multi_out=True)
    return apply(f, *args, name="fused_layer_norm")


def swiglu(x, y=None, name=None):
    """reference: incubate/nn/functional/swiglu.py — silu(x) * y; if y is
    None, x is split in half along the last dim. On TPU the two-operand
    form runs the one-pass Pallas kernel (fused_bias_act swiglu path)."""
    x = as_tensor(x)
    if y is None:
        if _use_pallas_fused():
            from ....ops.pallas import fused as _pf

            def fsplit(v):
                a, b = jnp.split(v, 2, axis=-1)
                return _pf.swiglu(a, b)
            return apply(fsplit, x, name="swiglu")

        def f(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a.astype(jnp.float32)).astype(v.dtype) * b
        return apply(f, x, name="swiglu")
    y = as_tensor(y)
    if _use_pallas_fused():
        from ....ops.pallas import fused as _pf
        return apply(lambda a, b: _pf.swiglu(a, b), x, y, name="swiglu")
    return apply(
        lambda a, b: jax.nn.silu(a.astype(jnp.float32)).astype(a.dtype) * b,
        x, y, name="swiglu")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    rotary_emb_base=10000.0, time_major=False):
    """reference: incubate/nn/functional/fused_rotary_position_embedding.py
    (kernel paddle/phi/kernels/fusion/fused_rope_kernel.cu). q/k/v:
    (B, S, H, D). Returns rotated (q, k, v) (None passthrough)."""
    outs = []
    tensors = [t for t in (q, k, v) if t is not None]
    q0 = as_tensor(tensors[0])
    B, S, H, D = q0.shape
    if sin is None or cos is None:
        inv = 1.0 / (rotary_emb_base **
                     (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        t = jnp.arange(S, dtype=jnp.float32)
        freqs = jnp.outer(t, inv)
        cos_t, sin_t = jnp.cos(freqs), jnp.sin(freqs)
    else:
        cos_t = as_tensor(cos)._value.reshape(S, -1)[:, :D // 2]
        sin_t = as_tensor(sin)._value.reshape(S, -1)[:, :D // 2]
    if position_ids is not None:
        pid = as_tensor(position_ids)._value  # (B, S)
        cos_t = jnp.take(cos_t, pid, axis=0)  # (B, S, D/2)
        sin_t = jnp.take(sin_t, pid, axis=0)
        expand = lambda c: c[:, :, None, :]
    else:
        expand = lambda c: c[None, :, None, :]

    # fully-fused Pallas path (fused_rope_kernel.cu's hot shape: neox
    # style, shared tables, q+k in one launch)
    if (_use_pallas_fused() and use_neox_rotary_style
            and position_ids is None and q is not None and k is not None
            and v is None):
        from ....ops.pallas import fused as _pf
        # the kernel reads (S, D) tables whose two halves repeat
        cos_full = jnp.concatenate([cos_t, cos_t], axis=-1)
        sin_full = jnp.concatenate([sin_t, sin_t], axis=-1)

        def frope(qv, kv):
            return _pf.rope_qk(qv, kv, cos_full, sin_full)
        rq, rk = apply(frope, as_tensor(q), as_tensor(k),
                       name="fused_rope", multi_out=True)
        return rq, rk, None

    def rot(t):
        def f(x):
            c = expand(cos_t).astype(jnp.float32)
            s = expand(sin_t).astype(jnp.float32)
            xf = x.astype(jnp.float32)
            if use_neox_rotary_style:
                x1, x2 = jnp.split(xf, 2, axis=-1)
                out = jnp.concatenate(
                    [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
            else:  # GPT-J interleaved pairs
                x1 = xf[..., 0::2]
                x2 = xf[..., 1::2]
                o1 = x1 * c - x2 * s
                o2 = x2 * c + x1 * s
                out = jnp.stack([o1, o2], axis=-1).reshape(xf.shape)
            return out.astype(x.dtype)
        return apply(f, as_tensor(t), name="fused_rope")

    result = tuple(rot(t) if t is not None else None for t in (q, k, v))
    return result


_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x.astype(jnp.float32)).astype(x.dtype),
    "relu": jax.nn.relu,
    "silu": lambda x: jax.nn.silu(x.astype(jnp.float32)).astype(x.dtype),
    "swiglu": None,  # handled specially
    "geglu": None,
}


def fused_bias_act(x, bias=None, act_method="gelu", **_):
    """reference: incubate/nn/functional/fused_bias_act (kernel
    fused_bias_act_kernel.cu): out = act(x + bias), with swiglu/geglu
    splitting the last dim."""
    x = as_tensor(x)
    args = [x]
    if bias is not None:
        args.append(as_tensor(bias))

    def f(v, *rest):
        if rest:
            v = v + rest[0]
        if act_method in ("swiglu", "geglu"):
            a, b = jnp.split(v, 2, axis=-1)
            g = (jax.nn.silu if act_method == "swiglu" else jax.nn.gelu)(
                a.astype(jnp.float32)).astype(v.dtype)
            return g * b
        return _ACTS[act_method](v)
    return apply(f, *args, name="fused_bias_act")


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """reference: incubate/nn/functional/fused_dropout_add.py —
    dropout(x) + y in one pass."""
    from ....nn.functional.common import dropout
    d = dropout(x, p=p, training=training, mode=mode)
    return d + as_tensor(y)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """reference: incubate/nn/functional/blha etc. fused_matmul_bias —
    cublasLt epilogue fusion; XLA does the same fusion natively."""
    x, y = as_tensor(x), as_tensor(y)
    args = [x, y]
    if bias is not None:
        args.append(as_tensor(bias))

    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        if rest:
            out = out + rest[0]
        return out
    return apply(f, *args, name="fused_matmul_bias")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    out = fused_matmul_bias(x, y, bias, transpose_x=trans_x,
                            transpose_y=trans_y)
    if activation in (None, "none"):
        return out
    return apply(_ACTS[activation], out, name=f"fused_linear_{activation}")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode='upscale_in_train',
                      name=None):
    """reference: incubate/nn/functional/fused_transformer.py
    fused_feedforward (kernel fused_feedforward_kernel.cu):
    residual + dropout(linear2(dropout(act(linear1(ln(x)))))) with pre/post
    layernorm."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    x = as_tensor(x)
    residual = x
    d = x.shape[-1]
    if pre_layer_norm:
        x = layer_norm(x, d, ln1_scale, ln1_bias, ln1_epsilon)
    h = fused_matmul_bias(x, linear1_weight, linear1_bias)
    h = apply(_ACTS.get(activation, jax.nn.relu), h, name=activation)
    h = dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_matmul_bias(h, linear2_weight, linear2_bias)
    h = dropout(h, p=dropout2_rate, training=training, mode=mode)
    out = residual + h
    if not pre_layer_norm:
        out = layer_norm(out, d, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode='upscale_in_train',
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """reference: fused_transformer.py fused_multi_head_attention (kernel
    fused_attention_kernel.cu). qkv_weight: (3, H, D_head, D_in) as in the
    reference layout."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    from ....nn.functional.attention import scaled_dot_product_attention
    x = as_tensor(x)
    residual = x
    B, S, D = x.shape
    if pre_layer_norm:
        x = layer_norm(x, D, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qkvw = as_tensor(qkv_weight)
    three, H, Dh, Din = qkvw.shape
    qkv = fused_matmul_bias(
        x, qkvw.reshape([3 * H * Dh, Din]), qkv_bias, transpose_y=True)
    qkv = qkv.reshape([B, S, 3, H, Dh])

    def split3(t):
        return (apply(lambda v: v[:, :, 0], t, name="slice_q"),
                apply(lambda v: v[:, :, 1], t, name="slice_k"),
                apply(lambda v: v[:, :, 2], t, name="slice_v"))
    q, k, v = split3(qkv)
    o = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate
        if training else 0.0, is_causal=False)
    o = o.reshape([B, S, H * Dh])
    out = fused_matmul_bias(o, linear_weight, linear_bias)
    out = dropout(out, p=dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm:
        out = layer_norm(out, D, ln_scale, ln_bias, ln_epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True,
                                           mode='upscale_in_train',
                                           name=None):
    """reference: incubate/nn/functional/fused_transformer.py."""
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    x = as_tensor(x)
    if bias is not None:
        x = x + as_tensor(bias)
    x = dropout(x, p=dropout_rate, training=training, mode=mode)
    out = x + as_tensor(residual)
    return layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               sequence_lengths=None, rotary_tensor=None,
                               out_shift=None, seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False, **_):
    """Decode-time single-token attention against a KV cache
    (reference: incubate/nn/functional/masked_multihead_attention.py,
    kernel masked_multihead_attention_kernel.cu).

    x: (B, 3*H*D) fused qkv for ONE step; cache_kv: (2, B, H, max_seq, D).
    Returns (out (B, H*D), updated cache_kv) following the reference.
    """
    x = as_tensor(x)
    cache = as_tensor(cache_kv)
    args = [x, cache]
    if bias is not None:
        args.append(as_tensor(bias))
    if sequence_lengths is not None:
        args.append(as_tensor(sequence_lengths))

    two, B, H, MS, D = cache.shape

    def f(xv, cachev, *rest):
        i = 0
        if bias is not None:
            xv = xv + rest[i]; i += 1
        if sequence_lengths is not None:
            cur = rest[i].reshape(-1)  # (B,) current lengths
        else:
            cur = None
        qkv = xv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # (B, H, D)
        if cur is None:
            # without explicit lengths, append at position 0 of empty cache
            step = jnp.zeros((B,), jnp.int32)
        else:
            step = cur.astype(jnp.int32)
        bidx = jnp.arange(B)
        ck = cachev[0].at[bidx, :, step].set(k)
        cv = cachev[1].at[bidx, :, step].set(v)
        # attention over cached positions <= step
        s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) / math.sqrt(D)
        pos = jnp.arange(MS)[None, None, :]
        s = jnp.where(pos <= step[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhs,bhsd->bhd", p.astype(cv.dtype), cv)
        return o.reshape(B, H * D), jnp.stack([ck, cv])

    return apply(f, *args, name="masked_multihead_attention", multi_out=True)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2,
              norm_topk_prob=True, **_):
    """reference: incubate/nn/functional/fused_moe.py — top-k routed expert
    FFN. ffn1_weight: (E, H, 2*I) swiglu-packed; ffn2: (E, I, H)."""
    from ....models.moe import MoEConfig, moe_ffn
    x = as_tensor(x)
    gw = as_tensor(gate_weight)
    w1 = as_tensor(ffn1_weight)
    w2 = as_tensor(ffn2_weight)
    E = gw.shape[-1]
    cfg = MoEConfig(num_experts=E, top_k=moe_topk, capacity_factor=4.0)

    def f(xv, gv, w1v, w2v):
        half = w1v.shape[-1] // 2
        params = {"w_gate": gv, "wg": w1v[..., :half],
                  "wu": w1v[..., half:], "wd": w2v}
        squeeze = xv.ndim == 2
        if squeeze:
            xv = xv[None]
        out, _ = moe_ffn(xv, params, cfg)
        return out[0] if squeeze else out
    return apply(f, x, gw, w1, w2, name="fused_moe")


def softmax_mask_fuse(x, mask, name=None):
    """Fused additive-mask softmax (reference:
    paddle/phi/kernels/fusion/gpu/fused_softmax_mask_kernel.cu;
    incubate/nn/functional/fused_softmax_mask.py). x (B, H, S, S) scores,
    mask (B, 1, S, S) additive (-inf style); softmax computed in fp32 —
    XLA fuses the add into the softmax."""
    def fn(xv, mv):
        s32 = xv.astype(jnp.float32) + mv.astype(jnp.float32)
        return jax.nn.softmax(s32, axis=-1).astype(xv.dtype)
    return apply(fn, as_tensor(x), as_tensor(mask),
                 name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal (upper-triangle-masked) softmax (reference:
    fused_softmax_mask_upper_triangle_kernel.cu)."""
    def fn(xv):
        S = xv.shape[-1]
        causal = jnp.tril(jnp.ones((S, S), bool))
        s32 = jnp.where(causal, xv.astype(jnp.float32),
                        jnp.finfo(jnp.float32).min)
        return jax.nn.softmax(s32, axis=-1).astype(xv.dtype)
    return apply(fn, as_tensor(x), name="softmax_mask_fuse_upper_triangle")
