from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward, FusedMultiHeadAttention, FusedTransformerEncoderLayer,
    FusedLinear, FusedDropoutAdd, FusedBiasDropoutResidualLayerNorm,
    FusedMultiTransformer, FusedDropout, FusedTransformer,
)
