"""ASP — automatic 2:4 structured sparsity (reference: python/paddle/
incubate/asp/ — asp.py decorate/prune_model, supported_layer_list).

TPU note: the MXU has no sparse-tensor-core analog, so 2:4 pruning here is
a *masking* workflow (same as the reference's training-time behavior):
``prune_model`` computes 2:4 masks per supported weight and ``decorate``
re-applies masks after each optimizer step, preserving the reference
semantics for model-quality experiments.
"""
from __future__ import annotations

from typing import Dict

import numpy as np
import jax.numpy as jnp

from ..._core.tensor import Tensor

_masks: Dict[int, np.ndarray] = {}


def calculate_density(x) -> float:
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    return float((arr != 0).sum() / arr.size)


def _mask_2to4_1d(v: np.ndarray) -> np.ndarray:
    """Keep the 2 largest-|.| of every 4 consecutive elements."""
    n = v.size - v.size % 4
    blocks = np.abs(v[:n]).reshape(-1, 4)
    order = np.argsort(-blocks, axis=1)
    mask = np.zeros_like(blocks, dtype=bool)
    rows = np.arange(blocks.shape[0])[:, None]
    mask[rows, order[:, :2]] = True
    full = np.ones(v.shape, dtype=bool)
    full[:n] = mask.reshape(-1)
    return full


def create_mask(w: np.ndarray) -> np.ndarray:
    if w.ndim < 2:
        return np.ones_like(w, dtype=bool)
    flat = w.reshape(-1, w.shape[-1])
    mask = np.stack([_mask_2to4_1d(row) for row in flat])
    return mask.reshape(w.shape)


def check_mask_2_4(mask: np.ndarray) -> bool:
    flat = mask.reshape(-1)
    n = flat.size - flat.size % 4
    return bool((flat[:n].reshape(-1, 4).sum(1) <= 2).all())


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every >=2D weight of the model in place."""
    from ...nn.layer.layers import Layer
    assert isinstance(model, Layer)
    for name, p in model.named_parameters():
        if p is None or p.ndim < 2 or "bias" in name:
            continue
        w = p.numpy()
        mask = create_mask(w)
        _masks[id(p)] = mask
        p.set_value(w * mask)
    return _masks


def decorate(optimizer):
    """Wrap optimizer.step to re-apply sparsity masks after each update
    (reference: asp.py decorate -> OptimizerWithSparsityGuarantee)."""
    orig_step = optimizer.step

    def step():
        orig_step()
        for group in [optimizer._parameter_list or []]:
            for p in group:
                mask = _masks.get(id(p))
                if mask is not None:
                    p.set_value(p.numpy() * mask)
    optimizer.step = step
    return optimizer


def reset_excluded_layers(main_program=None):
    pass


def set_excluded_layers(param_names, main_program=None):
    pass
