"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py). The gate owns the router weight and maps
token features -> (expert idx, combine weight, aux losses)."""
from __future__ import annotations

import jax.numpy as jnp

from .....nn.layer.layers import Layer
from .....models.moe import MoEConfig


class NaiveGate(Layer):
    """Plain top-k softmax routing, no aux loss."""

    top_k = 2

    def __init__(self, d_model, num_experts, world_size=1, topk=2):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = topk
        self.weight = self.create_parameter([d_model, num_experts])

    def config(self, capacity_factor=1.25) -> MoEConfig:
        return MoEConfig(num_experts=self.num_experts, top_k=self.top_k,
                         capacity_factor=capacity_factor,
                         aux_loss_weight=0.0, z_loss_weight=0.0)


class GShardGate(NaiveGate):
    """Top-2 with load-balancing aux loss (reference: gshard_gate.py)."""

    def __init__(self, d_model, num_experts, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_experts, world_size, topk)
        self.capacity_factor = capacity[0]

    def config(self, capacity_factor=None) -> MoEConfig:
        return MoEConfig(num_experts=self.num_experts, top_k=self.top_k,
                         capacity_factor=capacity_factor or
                         self.capacity_factor,
                         aux_loss_weight=0.01, z_loss_weight=1e-3)


class SwitchGate(NaiveGate):
    """Top-1 switch routing (reference: switch_gate.py)."""

    def __init__(self, d_model, num_experts, world_size=1, topk=1,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_experts, world_size, 1)
        self.capacity_factor = capacity[0]

    def config(self, capacity_factor=None) -> MoEConfig:
        return MoEConfig(num_experts=self.num_experts, top_k=1,
                         capacity_factor=capacity_factor or
                         self.capacity_factor,
                         aux_loss_weight=0.01, z_loss_weight=1e-3)
