"""Imperative MoE layer (reference: python/paddle/incubate/distributed/
models/moe/moe_layer.py:263 MoELayer — MoEScatter:99 / MoEGather:149 route
tokens through NCCL alltoall).

TPU-native: the Layer owns per-expert SwiGLU weights stacked (E, ...) and
delegates to the functional GShard dispatch (models/moe.py) — capacity-
based static shapes, einsum dispatch that GSPMD lowers to AllToAll when
the expert dim is sharded over an "ep" mesh axis. The gate is a
:class:`gate.NaiveGate`-family Layer for API parity.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .....nn.layer.layers import Layer
from ....._core.autograd import apply
from .....ops._registry import as_tensor
from .....models import moe as _moe
from .gate import NaiveGate, GShardGate, SwitchGate


class MoELayer(Layer):
    def __init__(self, d_model: int, d_hidden: int, num_experts: int,
                 gate: Optional[object] = None, top_k: int = 2,
                 capacity_factor: float = 1.25, group=None,
                 recompute_interval=0, **kw):
        super().__init__()
        if gate is None or gate == "gshard":
            gate = GShardGate(d_model, num_experts, topk=top_k)
        elif gate == "switch":
            gate = SwitchGate(d_model, num_experts)
        elif gate == "naive":
            gate = NaiveGate(d_model, num_experts, topk=top_k)
        self.gate = gate
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.wg = self.create_parameter([num_experts, d_model, d_hidden])
        self.wu = self.create_parameter([num_experts, d_model, d_hidden])
        self.wd = self.create_parameter([num_experts, d_hidden, d_model])
        self._last_aux_loss = None

    def forward(self, x):
        x = as_tensor(x)
        cfg = self.gate.config(self.capacity_factor)

        def f(xv, gw, wg, wu, wd):
            params = {"w_gate": gw, "wg": wg, "wu": wu, "wd": wd}
            squeeze = xv.ndim == 2
            if squeeze:
                xv = xv[None]
            out, losses = _moe.moe_ffn(xv, params, cfg)
            aux = losses["aux_loss"] + losses["z_loss"]
            return (out[0] if squeeze else out), aux

        out, aux = apply(f, x, self.gate.weight, self.wg, self.wu, self.wd,
                         name="moe_layer", multi_out=True)
        self._last_aux_loss = aux
        return out

    @property
    def aux_loss(self):
        """Load-balancing loss of the last forward (add to the objective)."""
        return self._last_aux_loss
