"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,
adam,adamw,adagrad,adadelta,rmsprop,adamax,lamb,lbfgs}.py). Update rules are
pure jnp functions applied eagerly or inside jit."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .optimizer import Optimizer


def _apply_l2(g, p, wd):
    if wd:
        return g + wd * p
    return g


class SGD(Optimizer):
    """reference: python/paddle/optimizer/sgd.py."""

    def _update_rule(self, p, g, state, lr, ctx):
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        return p - lr * g, state


class Momentum(Optimizer):
    """reference: python/paddle/optimizer/momentum.py."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _slots(self):
        return ("velocity",)

    def _context(self):
        return {"momentum": self._momentum, "nesterov": self._nesterov}

    def _update_rule(self, p, g, state, lr, ctx):
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        v = ctx["momentum"] * state["velocity"] + g
        if ctx["nesterov"]:
            upd = g + ctx["momentum"] * v
        else:
            upd = v
        state["velocity"] = v
        return p - lr * upd, state


class Adam(Optimizer):
    """reference: python/paddle/optimizer/adam.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _slots(self):
        return ("moment1", "moment2")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        state["moment1"] = m
        state["moment2"] = v
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), state


class AdamW(Adam):
    """reference: python/paddle/optimizer/adamw.py — decoupled decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._coupled_wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun

    def _context(self):
        c = super()._context()
        c["adamw_wd"] = self._coupled_wd
        c["decay_fn"] = self._apply_decay_param_fun
        return c

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        wd = ctx["adamw_wd"]
        decay_fn = ctx.get("decay_fn")
        do_decay = True
        param = ctx.get("param")
        if decay_fn is not None and param is not None:
            do_decay = decay_fn(param.name)
        if wd and do_decay:
            p32 = p32 * (1.0 - lr * wd)
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        state["moment1"] = m
        state["moment2"] = v
        return p32 - lr * mhat / (jnp.sqrt(vhat) + eps), state


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _slots(self):
        return ("moment", "inf_norm")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        state["moment"] = m
        state["inf_norm"] = u
        return p - (lr / (1 - b1 ** t)) * m / (u + eps), state


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _slots(self):
        return ("moment",)

    def _context(self):
        return {"eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        acc = state["moment"] + jnp.square(g)
        state["moment"] = acc
        return p - lr * g / (jnp.sqrt(acc) + ctx["eps"]), state


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _slots(self):
        return ("avg_squared_grad", "avg_squared_update")

    def _context(self):
        return {"eps": self._epsilon, "rho": self._rho}

    def _update_rule(self, p, g, state, lr, ctx):
        eps, rho = ctx["eps"], ctx["rho"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        sg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        upd = -jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(sg + eps) * g
        su = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        state["avg_squared_grad"] = sg
        state["avg_squared_update"] = su
        return p + lr * upd, state


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _slots(self):
        return ("mean_square", "mean_grad", "momentum_acc")

    def _context(self):
        return {"rho": self._rho, "eps": self._epsilon,
                "momentum": self._momentum, "centered": self._centered}

    def _update_rule(self, p, g, state, lr, ctx):
        rho, eps = ctx["rho"], ctx["eps"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        state["mean_square"] = ms
        if ctx["centered"]:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            state["mean_grad"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = ctx["momentum"] * state["momentum_acc"] + lr * g / denom
        state["momentum_acc"] = mom
        return p - mom, state


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py — layerwise-adapted Adam."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _slots(self):
        return ("moment1", "moment2")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon, "lamb_wd": self._lamb_wd,
                "exclude_fn": self._exclude_fn}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        wd = ctx["lamb_wd"]
        param = ctx.get("param")
        if ctx.get("exclude_fn") is not None and param is not None and \
                ctx["exclude_fn"](param):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        state["moment1"] = m
        state["moment2"] = v
        return p32 - lr * trust * r, state
