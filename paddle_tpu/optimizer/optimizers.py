"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,
adam,adamw,adagrad,adadelta,rmsprop,adamax,lamb,lbfgs}.py). Update rules are
pure jnp functions applied eagerly or inside jit."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .optimizer import Optimizer


def _apply_l2(g, p, wd):
    if wd:
        return g + wd * p
    return g


class SGD(Optimizer):
    """reference: python/paddle/optimizer/sgd.py."""

    def _update_rule(self, p, g, state, lr, ctx):
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        return p - lr * g, state


class Momentum(Optimizer):
    """reference: python/paddle/optimizer/momentum.py."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _slots(self):
        return ("velocity",)

    def _context(self):
        return {"momentum": self._momentum, "nesterov": self._nesterov}

    def _update_rule(self, p, g, state, lr, ctx):
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        v = ctx["momentum"] * state["velocity"] + g
        if ctx["nesterov"]:
            upd = g + ctx["momentum"] * v
        else:
            upd = v
        state["velocity"] = v
        return p - lr * upd, state


class Adam(Optimizer):
    """reference: python/paddle/optimizer/adam.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _slots(self):
        return ("moment1", "moment2")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        state["moment1"] = m
        state["moment2"] = v
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), state


class AdamW(Adam):
    """reference: python/paddle/optimizer/adamw.py — decoupled decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._coupled_wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun

    def _context(self):
        c = super()._context()
        c["adamw_wd"] = self._coupled_wd
        c["decay_fn"] = self._apply_decay_param_fun
        return c

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        wd = ctx["adamw_wd"]
        decay_fn = ctx.get("decay_fn")
        do_decay = True
        param = ctx.get("param")
        if decay_fn is not None and param is not None:
            do_decay = decay_fn(param.name)
        if wd and do_decay:
            p32 = p32 * (1.0 - lr * wd)
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        state["moment1"] = m
        state["moment2"] = v
        return p32 - lr * mhat / (jnp.sqrt(vhat) + eps), state


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _slots(self):
        return ("moment", "inf_norm")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        state["moment"] = m
        state["inf_norm"] = u
        return p - (lr / (1 - b1 ** t)) * m / (u + eps), state


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _slots(self):
        return ("moment",)

    def _context(self):
        return {"eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        acc = state["moment"] + jnp.square(g)
        state["moment"] = acc
        return p - lr * g / (jnp.sqrt(acc) + ctx["eps"]), state


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def _slots(self):
        return ("avg_squared_grad", "avg_squared_update")

    def _context(self):
        return {"eps": self._epsilon, "rho": self._rho}

    def _update_rule(self, p, g, state, lr, ctx):
        eps, rho = ctx["eps"], ctx["rho"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        sg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        upd = -jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(sg + eps) * g
        su = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        state["avg_squared_grad"] = sg
        state["avg_squared_update"] = su
        return p + lr * upd, state


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _slots(self):
        return ("mean_square", "mean_grad", "momentum_acc")

    def _context(self):
        return {"rho": self._rho, "eps": self._epsilon,
                "momentum": self._momentum, "centered": self._centered}

    def _update_rule(self, p, g, state, lr, ctx):
        rho, eps = ctx["rho"], ctx["eps"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        state["mean_square"] = ms
        if ctx["centered"]:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            state["mean_grad"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = ctx["momentum"] * state["momentum_acc"] + lr * g / denom
        state["momentum_acc"] = mom
        return p - mom, state


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py — layerwise-adapted Adam."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _slots(self):
        return ("moment1", "moment2")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon, "lamb_wd": self._lamb_wd,
                "exclude_fn": self._exclude_fn}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g32)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        wd = ctx["lamb_wd"]
        param = ctx.get("param")
        if ctx.get("exclude_fn") is not None and param is not None and \
                ctx["exclude_fn"](param):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        state["moment1"] = m
        state["moment2"] = v
        return p32 - lr * trust * r, state


class NAdam(Optimizer):
    """reference: python/paddle/optimizer/nadam.py — Adam with Nesterov
    momentum (Dozat 2016)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon = epsilon
        self._momentum_decay = momentum_decay

    def _slots(self):
        return ("moment1", "moment2", "mu_product")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon, "psi": self._momentum_decay}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps, psi = (ctx["beta1"], ctx["beta2"], ctx["eps"],
                            ctx["psi"])
        t = ctx["step"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * psi))
        mu_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
        # slot zeros mean "first step" (generic init paths create zeroed
        # slots; the product seed is 1)
        prev = jnp.where(state["mu_product"] == 0.0, 1.0,
                         state["mu_product"])
        mu_prod = prev * mu_t
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        mhat = (mu_t1 * m / (1 - mu_prod * mu_t1) +
                (1 - mu_t) * g / (1 - mu_prod))
        vhat = v / (1 - b2 ** t)
        state["moment1"], state["moment2"] = m, v
        state["mu_product"] = jnp.broadcast_to(
            mu_prod, state["moment1"].shape).astype(jnp.float32) \
            if jnp.ndim(mu_prod) == 0 else mu_prod
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), state


class RAdam(Optimizer):
    """reference: python/paddle/optimizer/radam.py — rectified Adam (Liu
    et al. 2020): falls back to unadapted momentum while the variance
    estimate is untrustworthy."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon = epsilon

    def _slots(self):
        return ("moment1", "moment2")

    def _context(self):
        return {"beta1": self._beta1, "beta2": self._beta2,
                "eps": self._epsilon}

    def _update_rule(self, p, g, state, lr, ctx):
        b1, b2, eps = ctx["beta1"], ctx["beta2"], ctx["eps"]
        t = ctx["step"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        state["moment1"], state["moment2"] = m, v
        mhat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2.0 * t * (b2 ** t) / (1 - b2 ** t)
        r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                     jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                 eps))
        vhat = jnp.sqrt(v / (1 - b2 ** t))
        adaptive = p - lr * r * mhat / (vhat + eps)
        plain = p - lr * mhat
        # threshold 5 per the reference (radam.py docstring) and torch
        return jnp.where(rho_t > 5.0, adaptive, plain), state


class Rprop(Optimizer):
    """reference: python/paddle/optimizer/rprop.py — resilient
    backpropagation (sign-based per-weight step sizes; full-batch only)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _slots(self):
        return ("prev_grad", "step_size")

    def _context(self):
        return {"etas": self._etas, "lr_range": self._lr_range,
                "lr0": self._learning_rate
                if isinstance(self._learning_rate, float) else 0.001}

    def _update_rule(self, p, g, state, lr, ctx):
        eta_n, eta_p = ctx["etas"]
        lo, hi = ctx["lr_range"]
        g = g.astype(jnp.float32)
        sz = jnp.where(state["step_size"] == 0.0,
                       jnp.full_like(state["step_size"], ctx["lr0"]),
                       state["step_size"])
        sign = jnp.sign(g * state["prev_grad"])
        sz = jnp.clip(jnp.where(sign > 0, sz * eta_p,
                                jnp.where(sign < 0, sz * eta_n, sz)),
                      lo, hi)
        # on sign change the step is skipped and the stored grad zeroed
        g_eff = jnp.where(sign < 0, 0.0, g)
        state["prev_grad"] = g_eff
        state["step_size"] = sz
        return p - jnp.sign(g_eff) * sz, state


class ASGD(Optimizer):
    """reference: python/paddle/optimizer/asgd.py — averaged SGD (Polyak
    averaging over the parameter trajectory)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._batch_num = batch_num

    def _slots(self):
        return ("d", "ys")

    def _context(self):
        return {"n": self._batch_num}

    def _update_rule(self, p, g, state, lr, ctx):
        # reference kernel: d += g - y_i; y_i = g; p -= lr/n * d
        n = ctx["n"]
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        d = state["d"] + g - state["ys"]
        state["d"] = d
        state["ys"] = g
        return p - lr / n * d, state


class Lars(Optimizer):
    """LARS — layer-wise adaptive rate scaling (reference:
    paddle/phi/kernels/gpu/lars_momentum_kernel.cu; fleet meta-optimizer
    lars_optimizer.py). Momentum with a per-parameter trust ratio
    ||w|| / (||g|| + lambda*||w||)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _slots(self):
        return ("velocity",)

    def _context(self):
        return {"mu": self._momentum, "coeff": self._lars_coeff,
                "wd": self._lars_wd, "eps": self._epsilon,
                "exclude": self._exclude}

    def _update_rule(self, p, g, state, lr, ctx):
        mu, coeff, wd, eps = (ctx["mu"], ctx["coeff"], ctx["wd"],
                              ctx["eps"])
        pname = (ctx.get("param_name")
                 or getattr(ctx.get("param"), "name", "") or "")
        if any(tok in pname for tok in ctx["exclude"]):
            wd = 0.0
        g = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        pn = jnp.sqrt(jnp.sum(jnp.square(p32)))
        gn = jnp.sqrt(jnp.sum(jnp.square(g)))
        # reference kernel (lars_momentum_kernel.cc): trust ratio only when
        # lars_weight_decay > 0 and both norms are positive; plain momentum
        # otherwise (excluded params train at the base LR)
        if wd > 0:
            trust = jnp.where(
                (pn > 0) & (gn > 0),
                coeff * pn / (gn + wd * pn + eps), 1.0)
        else:
            trust = 1.0
        v = mu * state["velocity"] + trust * lr * (g + wd * p32)
        state["velocity"] = v
        return p32 - v, state
