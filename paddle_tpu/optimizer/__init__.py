"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Momentum, Adam, AdamW, Adamax, Adagrad, Adadelta, RMSProp, Lamb,
    NAdam, RAdam, Rprop, ASGD, Lars,
)
from .lbfgs import LBFGS  # noqa: F401
from . import lr  # noqa: F401
