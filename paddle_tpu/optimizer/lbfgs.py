"""L-BFGS with strong-Wolfe line search.

reference: python/paddle/optimizer/lbfgs.py (closure-based step, history of
(s, y) pairs, two-loop recursion, _strong_wolfe line search with cubic
interpolation). Host-driven by design: L-BFGS is a full-batch method whose
control flow (bracketing, zoom) is data-dependent — each closure call is
one compiled forward/backward; the direction/line-search logic runs on
host scalars, which on TPU costs a few scalar transfers per iteration.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np
import jax.numpy as jnp

from .optimizer import Optimizer
from .._core.tensor import Tensor


def _gather_flat(ts):
    return jnp.concatenate([
        jnp.ravel(t._value).astype(jnp.float32) for t in ts])


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    """Cubic minimizer of a 1-D function from two (x, f, f') samples
    (reference: lbfgs.py _cubic_interpolate)."""
    if bounds is not None:
        lo, hi = bounds
    else:
        lo, hi = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    sq = d1 ** 2 - g1 * g2
    if sq >= 0:
        d2 = np.sqrt(sq)
        if x1 <= x2:
            x = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            x = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(x, lo), hi)
    return (lo + hi) / 2.0


def _strong_wolfe(obj, t, d_norm, f0, g0, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    """Find step t satisfying strong Wolfe conditions.
    obj(t) -> (f, directional derivative). reference: lbfgs.py
    _strong_wolfe."""
    f_prev, g_prev, t_prev = f0, g0, 0.0
    f_new, g_new = obj(t)
    ls_iter = 1
    # bracket phase
    bracket = None
    while ls_iter < max_ls:
        if f_new > f0 + c1 * t * g0 or (ls_iter > 1 and f_new >= f_prev):
            bracket = (t_prev, f_prev, g_prev, t, f_new, g_new)
            break
        if abs(g_new) <= -c2 * g0:
            return t, f_new, ls_iter
        if g_new >= 0:
            bracket = (t, f_new, g_new, t_prev, f_prev, g_prev)
            break
        t_next = _cubic_interpolate(t_prev, f_prev, g_prev, t, f_new, g_new,
                                    bounds=(t + 0.01 * (t - t_prev),
                                            t * 10))
        t_prev, f_prev, g_prev = t, f_new, g_new
        t = t_next
        f_new, g_new = obj(t)
        ls_iter += 1
    if bracket is None:
        return t, f_new, ls_iter
    # zoom phase
    lo_t, lo_f, lo_g, hi_t, hi_f, hi_g = bracket
    while ls_iter < max_ls:
        if abs(hi_t - lo_t) * d_norm < tolerance_change:
            break
        t = _cubic_interpolate(lo_t, lo_f, lo_g, hi_t, hi_f, hi_g)
        f_new, g_new = obj(t)
        ls_iter += 1
        if f_new > f0 + c1 * t * g0 or f_new >= lo_f:
            hi_t, hi_f, hi_g = t, f_new, g_new
        else:
            if abs(g_new) <= -c2 * g0:
                return t, f_new, ls_iter
            if g_new * (hi_t - lo_t) >= 0:
                hi_t, hi_f, hi_g = lo_t, lo_f, lo_g
            lo_t, lo_f, lo_g = t, f_new, g_new
    return lo_t, lo_f, ls_iter


class LBFGS(Optimizer):
    """reference: python/paddle/optimizer/lbfgs.py LBFGS — closure-based
    quasi-Newton. ``step(closure)``: closure clears grads, computes the
    loss, runs backward, returns the loss."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.max_iter = max_iter
        self.max_eval = max_eval if max_eval is not None \
            else max_iter * 5 // 4
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s_hist: List = []
        self._y_hist: List = []
        self._rho: List = []
        self._H_diag = 1.0
        self._first_iter = True

    # ---- flat-vector <-> params ----
    def _params(self):
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _set_flat(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.ndim else 1
            val = flat[off:off + n].reshape(tuple(p.shape)).astype(
                jnp.result_type(p._value))
            p._inplace_assign(val)
            off += n

    def _flat_grad(self):
        outs = []
        for p in self._params():
            g = p.grad
            gv = jnp.zeros(tuple(p.shape), jnp.float32) if g is None \
                else g._value.astype(jnp.float32)
            outs.append(jnp.ravel(gv))
        return jnp.concatenate(outs)

    @staticmethod
    def _loss_float(loss):
        return float(np.asarray(
            loss._value if isinstance(loss, Tensor) else loss))

    def step(self, closure: Optional[Callable] = None):
        if closure is None:
            raise ValueError("LBFGS.step needs a closure that recomputes "
                             "the loss and its gradients")
        loss = closure()
        loss_val = self._loss_float(loss)
        flat_grad = self._flat_grad()
        if float(jnp.abs(flat_grad).max()) <= self.tolerance_grad:
            return loss
        n_evals = 1
        lr = self.get_lr()

        for _ in range(self.max_iter):
            # ---- direction: two-loop recursion over history ----
            q = -flat_grad
            alphas = []
            for s, y, rho in zip(reversed(self._s_hist),
                                 reversed(self._y_hist),
                                 reversed(self._rho)):
                a = rho * float(jnp.dot(s, q))
                alphas.append(a)
                q = q - a * y
            d = q * self._H_diag
            for (s, y, rho), a in zip(zip(self._s_hist, self._y_hist,
                                          self._rho), reversed(alphas)):
                b = rho * float(jnp.dot(y, d))
                d = d + (a - b) * s

            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self.tolerance_change:
                break  # not a descent direction; history is stale
            x0 = _gather_flat(self._params())
            # reference: the gradient-scaled guess applies on the FIRST
            # iteration only; later iterations (with or without curvature
            # pairs) start from lr
            t = min(1.0, 1.0 / float(jnp.abs(flat_grad).sum())) * lr \
                if self._first_iter else lr
            self._first_iter = False

            if self.line_search_fn == "strong_wolfe":
                # cache the last evaluation so the accepted step's
                # loss/grad are reused instead of re-running the closure
                cache = {}

                def obj(step_size):
                    self._set_flat(x0 + step_size * d)
                    ls_loss = closure()
                    lf = self._loss_float(ls_loss)
                    fg = self._flat_grad()
                    cache["t"], cache["loss"] = step_size, ls_loss
                    cache["flat_grad"] = fg
                    return lf, float(jnp.dot(fg, d))
                d_norm = float(jnp.abs(d).max())
                t, loss_val, ls_evals = _strong_wolfe(
                    obj, t, d_norm, loss_val, gtd,
                    tolerance_change=self.tolerance_change)
                n_evals += ls_evals
                if cache.get("t") == t:
                    self._set_flat(x0 + t * d)
                    loss = cache["loss"]
                    new_flat_grad = cache["flat_grad"]
                else:
                    self._set_flat(x0 + t * d)
                    loss = closure()
                    loss_val = self._loss_float(loss)
                    new_flat_grad = self._flat_grad()
                    n_evals += 1
            else:
                self._set_flat(x0 + t * d)
                loss = closure()
                loss_val = self._loss_float(loss)
                new_flat_grad = self._flat_grad()
                n_evals += 1

            # ---- curvature update ----
            s = t * d
            y = new_flat_grad - flat_grad
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                if len(self._s_hist) >= self.history_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
                    self._rho.pop(0)
                self._s_hist.append(s)
                self._y_hist.append(y)
                self._rho.append(1.0 / ys)
                self._H_diag = ys / float(jnp.dot(y, y))
            flat_grad = new_flat_grad

            if float(jnp.abs(flat_grad).max()) <= self.tolerance_grad:
                break
            if float(jnp.abs(s).max()) <= self.tolerance_change:
                break
            if n_evals >= self.max_eval:
                break
        self._global_step += 1
        return loss
