"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:127).

Each optimizer defines a pure ``_update_rule(param, grad, state, lr, ctx) ->
(new_param, new_state)`` over raw jax arrays. The eager ``step()`` applies it
per-parameter (the reference's dygraph path); the same rule is reused
functionally by the jit train-step builder (paddle_tpu.jit.train_step) and by
the distributed sharding wrappers — one source of truth, two execution modes.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor, Parameter
from .._core.autograd import no_grad
from .._core import dtype as dtypes
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is not None:
            parameters = list(parameters)
            if parameters and isinstance(parameters[0], dict):
                # param groups (reference: optimizer.py _param_groups)
                self._param_groups = parameters
                parameters = [p for g in parameters for p in g["params"]]
            else:
                self._param_groups = None
        else:
            self._param_groups = None
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
        else:
            self._weight_decay = weight_decay  # None or L2Decay-like
        # state: slot name -> {id(param): Tensor}
        self._accumulators: Dict[str, Dict[int, Tensor]] = {}
        self._aux: Dict[str, Any] = {}
        self._global_step = 0

    # ---- lr ----
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the lr is an LRScheduler; call "
                "scheduler.step() instead")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- state accessors ----
    def _acc(self, name: str, p: Tensor, init=None, dtype=None) -> Tensor:
        slot = self._accumulators.setdefault(name, {})
        t = slot.get(id(p))
        if t is None:
            d = dtype or (jnp.float32 if p.dtype in (
                dtypes.float16, dtypes.bfloat16) else p.dtype)
            val = jnp.zeros(tuple(p.shape), d) if init is None else init
            t = Tensor(val, _internal=True)
            slot[id(p)] = t
        return t

    # ---- subclass interface ----
    def _slots(self) -> Sequence[str]:
        return ()

    def _update_rule(self, p, g, state: Dict[str, Any], lr, ctx: Dict) \
            -> Tuple[Any, Dict[str, Any]]:
        raise NotImplementedError

    def _context(self) -> Dict:
        return {}

    # ---- main entry points ----
    @no_grad()
    def step(self):
        lr = self.get_lr()
        params_grads = []
        wd_map = {}
        if self._param_groups is not None:
            for group in self._param_groups:
                glr = lr * group.get("learning_rate", 1.0)
                gwd = group.get("weight_decay", self._weight_decay)
                for p in group["params"]:
                    if not p.stop_gradient and p.grad is not None:
                        params_grads.append((p, p.grad, glr))
                        wd_map[id(p)] = gwd
        else:
            for p in self._parameter_list:
                if not p.stop_gradient and p.grad is not None:
                    plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                        if hasattr(p, "optimize_attr") else lr
                    params_grads.append((p, p.grad, plr))
                    wd_map[id(p)] = self._weight_decay
        if self._grad_clip is not None:
            clipped = self._grad_clip([(p, g) for p, g, _ in params_grads])
            params_grads = [(p, g, plr) for (p, _, plr), (_, g) in
                            zip(params_grads, clipped)]
        self._global_step += 1
        ctx = self._context()
        ctx["step"] = self._global_step
        for p, g, plr in params_grads:
            ctx["weight_decay"] = wd_map.get(id(p))
            ctx["param"] = p
            ctx["param_name"] = getattr(p, "name", "")
            state = {s: self._acc(s, p) for s in self._slots()}
            sv = {k: t._value for k, t in state.items()}
            # master weights: low-precision params update an fp32 master
            # copy and are re-cast each step (reference: multi_precision
            # kernels, e.g. adamw master_weight path)
            use_master = p.dtype in (dtypes.float16, dtypes.bfloat16)
            if use_master:
                master = self._acc("master", p, init=getattr(
                    p, "_master", None)._value if getattr(
                        p, "_master", None) is not None
                    else p._value.astype(jnp.float32))
                pv = master._value
            else:
                pv = p._value
            new_p, new_s = self._update_rule(pv, g._value, sv, plr, ctx)
            if use_master:
                master._inplace_assign(new_p.astype(jnp.float32))
            p._inplace_assign(new_p.astype(p.dtype))
            for k, t in state.items():
                t._inplace_assign(new_s[k])

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, None

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in (self._parameter_list or []):
            p.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    # ---- state dict ----
    def state_dict(self):
        sd = {}
        names = self._param_names()
        for slot, d in self._accumulators.items():
            for pid, t in d.items():
                pname = names.get(pid, str(pid))
                sd[f"{pname}@{slot}"] = t
        sd["@global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        names = {v: k for k, v in self._param_names().items()}
        for key, val in state_dict.items():
            if key == "@global_step":
                self._global_step = int(val)
                continue
            if key == "LR_Scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(val)
                continue
            if "@" not in key:
                continue
            pname, slot = key.rsplit("@", 1)
            pid = names.get(pname)
            if pid is None:
                continue
            d = self._accumulators.setdefault(slot, {})
            v = val._value if isinstance(val, Tensor) else jnp.asarray(
                np.asarray(val))
            if pid in d:
                d[pid]._inplace_assign(v)
            else:
                d[pid] = Tensor(v, _internal=True)

    def _param_names(self):
        return {id(p): p.name for p in (self._parameter_list or [])}

    # ---- functional core for jit/train_step ----
    def build_functional(self, named_params: Dict[str, Tensor]):
        """Return (init_state_fn, update_fn) closed over static config.

        update_fn(params, grads, state, step) -> (new_params, new_state),
        pure over pytrees — this is what jit-compiled training steps and
        sharded optimizers call.
        """
        slots = tuple(self._slots())
        ctx_static = self._context()
        wd = self._weight_decay
        rule = self._update_rule
        lr_holder = self

        def init_state(params):
            state = {}
            for k, p in params.items():
                low = jnp.result_type(p) in (jnp.float16, jnp.bfloat16)
                d = jnp.float32 if low else jnp.result_type(p)
                st = {s: jnp.zeros(jnp.shape(p), d) for s in slots}
                if low:
                    # fp32 master copy for low-precision params
                    st["master"] = jnp.asarray(p, jnp.float32)
                state[k] = st
            return state

        def update(params, grads, state, step, lr=None):
            lr = lr_holder.get_lr() if lr is None else lr
            new_params, new_state = {}, {}
            for k, p in params.items():
                g = grads.get(k)
                if g is None:
                    new_params[k] = p
                    new_state[k] = state[k]
                    continue
                ctx = dict(ctx_static)
                ctx["step"] = step
                ctx["weight_decay"] = wd
                ctx["param"] = None
                ctx["param_name"] = k
                st = dict(state[k])
                pv = st.get("master", p)
                np_, ns = rule(pv, g, st, lr, ctx)
                if "master" in st:
                    ns = dict(ns)
                    ns["master"] = np_.astype(jnp.float32)
                new_params[k] = np_.astype(jnp.result_type(p))
                new_state[k] = ns
            return new_params, new_state

        return init_state, update

    @property
    def _parameters(self):
        return self._parameter_list
