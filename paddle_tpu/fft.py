"""paddle.fft parity (reference: python/paddle/fft.py — wraps phi fft
kernels backed by cuFFT/pocketfft). TPU-native: jnp.fft lowers to XLA's
FFT HLO which runs on the TPU's transcendental units."""
from __future__ import annotations

import jax.numpy as jnp

from ._core.autograd import apply
from .ops._registry import as_tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _wrap1(fname):
    jf = getattr(jnp.fft, fname)

    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply(lambda v: jf(v, n=n, axis=axis, norm=norm),
                     as_tensor(x), name=f"fft_{fname}")
    op.__name__ = fname
    return op


def _wrapN(fname):
    jf = getattr(jnp.fft, fname)

    def op(x, s=None, axes=None, norm="backward", name=None):
        kw = {"s": s, "norm": norm}
        if axes is not None:
            kw["axes"] = axes
        return apply(lambda v: jf(v, **kw), as_tensor(x),
                     name=f"fft_{fname}")
    op.__name__ = fname
    return op


fft = _wrap1("fft")
ifft = _wrap1("ifft")
rfft = _wrap1("rfft")
irfft = _wrap1("irfft")
hfft = _wrap1("hfft")
ihfft = _wrap1("ihfft")
fftn = _wrapN("fftn")
ifftn = _wrapN("ifftn")
rfftn = _wrapN("rfftn")
irfftn = _wrapN("irfftn")


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ._core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d), _internal=True)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ._core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d), _internal=True)


def fftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.fftshift(v, axes=axes), as_tensor(x),
                 name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply(lambda v: jnp.fft.ifftshift(v, axes=axes), as_tensor(x),
                 name="ifftshift")


def _hermitian_nd(x, s, axes, norm, inverse):
    """Shared body of hfft2/hfftn (and the ihfft* inverses): Hermitian
    symmetry lives in the LAST transform axis (hfft/ihfft there); the
    remaining axes take regular complex (i)ffts — the reference's
    decomposition (python/paddle/fft.py hfftn)."""
    ax = list(axes) if axes is not None else None

    def f(v):
        if ax is not None:
            axs = ax
        elif s is not None:
            # numpy/reference semantics: no axes + explicit s -> the LAST
            # len(s) axes are transformed
            axs = list(range(v.ndim - len(s), v.ndim))
        else:
            axs = list(range(v.ndim))
        ss = list(s) if s is not None else [None] * len(axs)
        if inverse:
            out = jnp.fft.ihfft(v, n=ss[-1], axis=axs[-1], norm=norm)
            for a, n_ in zip(axs[:-1], ss[:-1]):
                out = jnp.fft.ifft(out, n=n_, axis=a, norm=norm)
        else:
            out = v
            for a, n_ in zip(axs[:-1], ss[:-1]):
                out = jnp.fft.fft(out, n=n_, axis=a, norm=norm)
            out = jnp.fft.hfft(out, n=ss[-1], axis=axs[-1], norm=norm)
        return out
    return apply(f, as_tensor(x), name="hfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """reference: fft.py hfft2 — 2-D FFT of a Hermitian-symmetric input."""
    return _hermitian_nd(x, s, axes, norm, inverse=False)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """reference: fft.py ihfft2."""
    return _hermitian_nd(x, s, axes, norm, inverse=True)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """reference: fft.py hfftn."""
    return _hermitian_nd(x, s, axes, norm, inverse=False)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """reference: fft.py ihfftn."""
    return _hermitian_nd(x, s, axes, norm, inverse=True)


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
