"""Text datasets (reference: python/paddle/text/datasets/{imdb,imikolov,
movielens,uci_housing,wmt14,wmt16}.py — download+parse into map-style
datasets).

Zero-egress environment: each dataset parses a LOCAL archive/file passed
via ``data_file`` (same formats the reference downloads); without it a
clear error points at the expected source. UCIHousing additionally ships
a built-in synthetic fallback so examples/tests run offline.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile
from typing import Optional

import numpy as np

from ..io.dataset import Dataset

_MISSING = ("{name}: no data_file given and downloads are disabled in this "
            "environment. Pass data_file=<path to {hint}>.")


class UCIHousing(Dataset):
    """reference: text/datasets/uci_housing.py (13 features, 1 target)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 download: bool = False):
        if data_file is not None:
            if not os.path.exists(data_file):
                raise FileNotFoundError(
                    f"UCIHousing: data_file {data_file!r} does not exist")
            raw = np.loadtxt(data_file)
        else:  # deterministic synthetic fallback, same shape/scale
            rng = np.random.default_rng(2024)
            X = rng.standard_normal((506, 13)).astype(np.float64)
            w = rng.standard_normal(13)
            y = X @ w + 0.1 * rng.standard_normal(506)
            raw = np.concatenate([X, y[:, None]], axis=1)
        raw = raw.astype(np.float32)
        split = int(0.8 * len(raw))
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """reference: text/datasets/imdb.py — builds word dict from the aclImdb
    tarball, yields (token_ids, label)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, download: bool = False):
        if not data_file or not os.path.exists(data_file):
            raise RuntimeError(_MISSING.format(
                name="Imdb", hint="aclImdb_v1.tar.gz"))
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if pat.match(m.name):
                    text = tf.extractfile(m).read().decode(
                        "utf-8", "ignore").lower()
                    toks = re.findall(r"[a-z]+", text)
                    docs.append(toks)
                    labels.append(0 if "/pos/" in m.name else 1)
                    for t in toks:
                        freq[t] = freq.get(t, 0) + 1
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c >= cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(t, unk) for t in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """reference: text/datasets/imikolov.py — PTB n-gram dataset."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size=5, mode="train", min_word_freq=50,
                 download: bool = False):
        if not data_file or not os.path.exists(data_file):
            raise RuntimeError(_MISSING.format(
                name="Imikolov", hint="simple-examples.tgz"))
        name = f"./simple-examples/data/ptb.{mode}.txt"
        with tarfile.open(data_file) as tf:
            lines = tf.extractfile(name).read().decode().splitlines()
        freq = {}
        corpus = []
        for ln in lines:
            toks = ln.strip().split() + ["<e>"]
            corpus.append(toks)
            for t in toks:
                freq[t] = freq.get(t, 0) + 1
        vocab = [w for w, c in freq.items() if c >= min_word_freq]
        self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
        self.word_idx["<unk>"] = unk = len(self.word_idx)
        self.data = []
        for toks in corpus:
            ids = [self.word_idx.get(t, unk) for t in toks]
            for i in range(len(ids) - window_size + 1):
                self.data.append(np.asarray(ids[i:i + window_size],
                                            np.int64))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _NeedsFile(Dataset):
    _hint = ""

    def __init__(self, data_file: Optional[str] = None, **kw):
        if not data_file or not os.path.exists(data_file):
            raise RuntimeError(_MISSING.format(
                name=type(self).__name__, hint=self._hint))
        self._file = data_file


class Movielens(_NeedsFile):
    _hint = "ml-1m.zip"


class WMT14(_NeedsFile):
    _hint = "wmt14.tgz"


class WMT16(_NeedsFile):
    _hint = "wmt16.tar.gz"


class Conll05st(_NeedsFile):
    """reference: text/datasets/conll05.py — CoNLL-2005 SRL dataset
    (semantic role labeling): returns (pred_idx, mark, *ctx_windows,
    label) per sample when a local data file is provided."""

    _hint = "conll05st-release (test.wsj words/props files)"

    def __init__(self, data_file: Optional[str] = None,
                 word_dict_file: Optional[str] = None,
                 verb_dict_file: Optional[str] = None,
                 target_dict_file: Optional[str] = None, **kw):
        super().__init__(data_file, **kw)
        self.samples: list = []
        # simple two-column (word, tag) per line, blank between sentences
        words, tags = [], []
        with open(self._file, "r", encoding="utf-8",
                  errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    if words:
                        self.samples.append((words, tags))
                        words, tags = [], []
                    continue
                parts = line.split()
                words.append(parts[0])
                tags.append(parts[-1] if len(parts) > 1 else "O")
        if words:
            self.samples.append((words, tags))
        vocab = {}
        labels = {}
        for ws, ts in self.samples:
            for w in ws:
                vocab.setdefault(w, len(vocab))
            for t in ts:
                labels.setdefault(t, len(labels))
        self.word_dict = vocab
        self.label_dict = labels

    def __getitem__(self, idx):
        ws, ts = self.samples[idx]
        import numpy as _np
        return (_np.asarray([self.word_dict[w] for w in ws], _np.int64),
                _np.asarray([self.label_dict[t] for t in ts], _np.int64))

    def __len__(self):
        return len(self.samples)
