"""paddle.text parity (reference: python/paddle/text/ — ViterbiDecoder /
viterbi_decode ops, datasets Imdb/Imikolov/Movielens/UCIHousing/WMT14/16).

Datasets require downloads (zero-egress here): constructors accept
``data_file`` for pre-fetched archives and raise a clear error otherwise.
"""
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
from .datasets import (  # noqa: F401
    Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
    Conll05st,
)
