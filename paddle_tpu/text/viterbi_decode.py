"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py,
kernel paddle/phi/kernels/viterbi_decode_kernel.h).

TPU-native: the DP over time steps is a lax.scan; argmax backtracking is a
reverse scan — whole decode jit-compiles."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..nn.layer.layers import Layer
from .._core.autograd import apply
from ..ops._registry import as_tensor


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """potentials: (B, T, N) emission scores; transition: (N, N).
    Returns (scores (B,), paths (B, T))."""
    potentials = as_tensor(potentials)
    transition_params = as_tensor(transition_params)
    args = [potentials, transition_params]
    if lengths is not None:
        args.append(as_tensor(lengths))

    def f(emis, trans, *rest):
        B, T, N = emis.shape
        lens = rest[0].astype(jnp.int32) if rest else \
            jnp.full((B,), T, jnp.int32)
        if include_bos_eos_tag:
            # reference semantics (viterbi_decode_kernel.cc): row N-1 =
            # start transitions, row N-2 = stop transitions
            start = emis[:, 0] + trans[N - 1][None, :]
        else:
            start = emis[:, 0]

        def step(carry, t):
            alpha = carry                                  # (B, N)
            # score for arriving at j from best i
            s = alpha[:, :, None] + trans[None]            # (B, N, N)
            best = jnp.max(s, axis=1) + emis[:, t]
            back = jnp.argmax(s, axis=1)                   # (B, N)
            # freeze alpha past each sequence's length
            mask = (t < lens)[:, None]
            new = jnp.where(mask, best, alpha)
            return new, back

        alpha, backs = lax.scan(step, start, jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + trans[N - 2][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # (B,)

        def backtrack(carry, bk_t):
            tag, t = carry
            bk, tt = bk_t
            prev = jnp.take_along_axis(bk, tag[:, None], axis=1)[:, 0]
            # only backtrack within the sequence
            newtag = jnp.where(tt < lens, prev.astype(jnp.int32), tag)
            return (newtag, t), newtag

        (_, _), path_rev = lax.scan(
            backtrack, (last, 0),
            (backs[::-1], jnp.arange(T - 1, 0, -1)))
        paths = jnp.concatenate(
            [path_rev[::-1].transpose(1, 0), last[:, None]], axis=1)
        return scores, paths

    return apply(f, *args, name="viterbi_decode", multi_out=True)


class ViterbiDecoder(Layer):
    """reference: text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = as_tensor(transitions)
        self._include = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self._include)
