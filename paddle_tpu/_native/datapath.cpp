// Fast host-side data path — native runtime component.
//
// Re-design of the reference's C++ data feed pipeline
// (reference: paddle/fluid/framework/data_feed.cc, data_set.cc — native
// readers/collators feeding the trainers without the GIL).
//
// Provides multi-threaded batch collation (stack N sample buffers into one
// contiguous batch) and RNG-seeded index shuffling, both GIL-released hot
// loops called from the DataLoader.
#include <algorithm>
#include <cstdint>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// Stack n sample buffers (each `bytes` long) into out (n*bytes).
void pt_collate(const void** samples, int64_t n, int64_t bytes, void* out,
                int num_threads) {
  if (num_threads <= 1 || n < 4) {
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(static_cast<char*>(out) + i * bytes, samples[i],
                  static_cast<size_t>(bytes));
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    int64_t lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(static_cast<char*>(out) + i * bytes, samples[i],
                    static_cast<size_t>(bytes));
    });
  }
  for (auto& t : ts) t.join();
}

// Fisher-Yates shuffle of [0, n) with a fixed seed (epoch-deterministic,
// matching the reference's DistributedBatchSampler seeding).
void pt_shuffle_indices(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  std::mt19937_64 rng(seed);
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = rng() % static_cast<uint64_t>(i + 1);
    std::swap(out[i], out[j]);
  }
}

// uint8 HWC image batch -> float32 NCHW with per-channel mean/std
// (the torchvision-style normalize+transpose hot loop).
void pt_normalize_nhwc_to_nchw(const uint8_t* in, int64_t n, int64_t h,
                               int64_t w, int64_t c, const float* mean,
                               const float* stdv, float* out,
                               int num_threads) {
  auto work = [=](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* img = in + i * h * w * c;
      float* dst = out + i * c * h * w;
      for (int64_t ch = 0; ch < c; ++ch) {
        float m = mean[ch], s = stdv[ch];
        float inv = 1.0f / (255.0f * s);
        for (int64_t p = 0; p < h * w; ++p)
          dst[ch * h * w + p] =
              (static_cast<float>(img[p * c + ch]) ) * inv - m / s;
      }
    }
  };
  if (num_threads <= 1 || n < 4) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    int64_t lo = t * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
