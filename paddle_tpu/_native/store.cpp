// TCP coordination store — native runtime component.
//
// Re-design of the reference's TCPStore
// (reference: paddle/phi/core/distributed/store/tcp_store.h:121 TCPStore,
// socket.cpp): the master rank runs a KV server; workers connect over TCP
// for set/get/wait/add — used for rendezvous (exchanging coordinator
// addresses / run metadata) and cross-process barriers before the JAX
// coordination service is up.
//
// Protocol (all little-endian):
//   request:  u8 op | u32 klen | key | u32 vlen | value
//   ops: 0=SET 1=GET 2=WAIT(blocking get) 3=ADD(i64 delta) 4=PING
//   response: u32 vlen | value   (ADD returns 8-byte i64; PING echoes)
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct KVState {
  std::map<std::string, std::string> data;
  std::mutex mu;
  std::condition_variable cv;
};

struct Server {
  int listen_fd = -1;
  std::atomic<bool> running{false};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  KVState kv;
  std::mutex handlers_mu;
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, p + got, n - got, 0);
    if (r <= 0) return false;
    got += static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  size_t sent = 0;
  while (sent < n) {
    ssize_t r = ::send(fd, p + sent, n - sent, MSG_NOSIGNAL);
    if (r <= 0) return false;
    sent += static_cast<size_t>(r);
  }
  return true;
}

bool send_value(int fd, const std::string& v) {
  uint32_t len = static_cast<uint32_t>(v.size());
  if (!write_exact(fd, &len, 4)) return false;
  return v.empty() || write_exact(fd, v.data(), v.size());
}

void handle_conn(Server* s, int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  while (s->running.load()) {
    uint8_t op;
    if (!read_exact(fd, &op, 1)) break;
    uint32_t klen;
    if (!read_exact(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_exact(fd, key.data(), klen)) break;
    uint32_t vlen;
    if (!read_exact(fd, &vlen, 4)) break;
    std::string val(vlen, '\0');
    if (vlen && !read_exact(fd, val.data(), vlen)) break;

    if (op == 0) {  // SET
      {
        std::lock_guard<std::mutex> g(s->kv.mu);
        s->kv.data[key] = val;
      }
      s->kv.cv.notify_all();
      if (!send_value(fd, "")) break;
    } else if (op == 1) {  // GET (non-blocking; empty if missing)
      std::string out;
      {
        std::lock_guard<std::mutex> g(s->kv.mu);
        auto it = s->kv.data.find(key);
        if (it != s->kv.data.end()) out = it->second;
      }
      if (!send_value(fd, out)) break;
    } else if (op == 2) {  // WAIT: block until key exists
      std::unique_lock<std::mutex> g(s->kv.mu);
      s->kv.cv.wait(g, [&] {
        return !s->running.load() ||
               s->kv.data.find(key) != s->kv.data.end();
      });
      std::string out;
      auto it = s->kv.data.find(key);
      if (it != s->kv.data.end()) out = it->second;
      g.unlock();
      if (!send_value(fd, out)) break;
    } else if (op == 3) {  // ADD: value is i64 delta; returns new value
      int64_t delta = 0;
      std::memcpy(&delta, val.data(), std::min<size_t>(8, val.size()));
      int64_t cur = 0;
      {
        std::lock_guard<std::mutex> g(s->kv.mu);
        auto it = s->kv.data.find(key);
        if (it != s->kv.data.end() && it->second.size() == 8)
          std::memcpy(&cur, it->second.data(), 8);
        cur += delta;
        std::string stored(8, '\0');
        std::memcpy(stored.data(), &cur, 8);
        s->kv.data[key] = stored;
      }
      s->kv.cv.notify_all();
      std::string out(8, '\0');
      std::memcpy(out.data(), &cur, 8);
      if (!send_value(fd, out)) break;
    } else if (op == 4) {  // PING
      if (!send_value(fd, "pong")) break;
    } else {
      break;
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// ---- server ----
void* pt_store_server_start(int port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) { delete s; return nullptr; }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->running.store(true);
  s->accept_thread = std::thread([s] {
    while (s->running.load()) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> g(s->handlers_mu);
      s->handlers.emplace_back(handle_conn, s, fd);
    }
  });
  return s;
}

void pt_store_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  if (!s) return;
  s->running.store(false);
  s->kv.cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    std::lock_guard<std::mutex> g(s->handlers_mu);
    for (auto& t : s->handlers)
      if (t.joinable()) t.join();
  }
  delete s;
}

// ---- client ----
int pt_store_client_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  // retry until timeout (master may not be up yet — reference behavior)
  int waited = 0;
  while (::connect(fd, reinterpret_cast<sockaddr*>(&addr),
                   sizeof(addr)) != 0) {
    ::close(fd);
    if (waited >= timeout_ms) return -1;
    ::usleep(100 * 1000);
    waited += 100;
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

void pt_store_client_close(int fd) {
  if (fd >= 0) ::close(fd);
}

// request; returns malloc'd value via out params. rc 0 ok, -1 io error.
int pt_store_request(int fd, int op, const char* key, int klen,
                     const char* val, int vlen, char** out, int* out_len) {
  uint8_t op8 = static_cast<uint8_t>(op);
  uint32_t kl = static_cast<uint32_t>(klen);
  uint32_t vl = static_cast<uint32_t>(vlen);
  if (!write_exact(fd, &op8, 1) || !write_exact(fd, &kl, 4) ||
      (kl && !write_exact(fd, key, kl)) || !write_exact(fd, &vl, 4) ||
      (vl && !write_exact(fd, val, vl)))
    return -1;
  uint32_t rlen;
  if (!read_exact(fd, &rlen, 4)) return -1;
  char* buf = static_cast<char*>(::malloc(rlen ? rlen : 1));
  if (rlen && !read_exact(fd, buf, rlen)) {
    ::free(buf);
    return -1;
  }
  *out = buf;
  *out_len = static_cast<int>(rlen);
  return 0;
}

void pt_store_free(void* p) { ::free(p); }

}  // extern "C"
