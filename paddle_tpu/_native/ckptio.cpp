// Parallel checkpoint chunk IO.
//
// reference: paddle/fluid/distributed/collective/async_load.cc (dedicated
// transfer threads + event sync) and the save_combine/load_combine kernels
// (paddle/phi/kernels/save_combine_kernel.h) — the native file path under
// the reference's checkpoint stack. TPU-native port: the distributed
// checkpoint writes raw row-major chunks; this module gives it
// multi-threaded pwrite/pread so large shards saturate NVMe/FUSE
// throughput instead of a single-thread memcpy loop.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

long long run_parallel(int fd, char* base, long long nbytes, int n_threads,
                       bool write) {
  int nt = std::max(1, std::min(n_threads, 16));
  if (nbytes < (1 << 20)) nt = 1;  // small files: thread spawn dominates
  long long chunk = (nbytes + nt - 1) / nt;
  std::vector<std::thread> threads;
  std::vector<long long> status(nt, 0);
  for (int i = 0; i < nt; ++i) {
    threads.emplace_back([=, &status]() {
      long long off = static_cast<long long>(i) * chunk;
      long long end = std::min(nbytes, off + chunk);
      while (off < end) {
        ssize_t n = write ? ::pwrite(fd, base + off, end - off, off)
                          : ::pread(fd, base + off, end - off, off);
        if (n <= 0) {
          status[i] = -(n == 0 ? EIO : errno);
          return;
        }
        off += n;
      }
    });
  }
  for (auto& t : threads) t.join();
  for (auto s : status)
    if (s < 0) return s;
  return nbytes;
}

}  // namespace

extern "C" {

// Write nbytes from data to path with n_threads parallel pwrites.
// Returns nbytes on success, -errno on failure. fsyncs before returning
// (the checkpointer's atomic tmp+rename contract needs durable content).
long long pt_file_write(const char* path, const void* data, long long nbytes,
                        int n_threads) {
  int fd = ::open(path, O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  long long rc = nbytes;
  if (::ftruncate(fd, nbytes) != 0) {
    rc = -errno;
  } else if (nbytes > 0) {
    rc = run_parallel(fd, const_cast<char*>(static_cast<const char*>(data)),
                      nbytes, n_threads, /*write=*/true);
  }
  if (rc >= 0 && ::fsync(fd) != 0) rc = -errno;
  ::close(fd);
  return rc;
}

// Read exactly nbytes from path into data with n_threads parallel preads.
// Returns nbytes on success, -errno on failure (including short files).
long long pt_file_read(const char* path, void* data, long long nbytes,
                       int n_threads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    int e = errno;
    ::close(fd);
    return -e;
  }
  if (st.st_size < nbytes) {
    ::close(fd);
    return -EIO;  // truncated chunk: fail loudly, never zero-fill
  }
  long long rc = nbytes > 0
      ? run_parallel(fd, static_cast<char*>(data), nbytes, n_threads,
                     /*write=*/false)
      : 0;
  ::close(fd);
  return rc < 0 ? rc : nbytes;
}

}  // extern "C"
