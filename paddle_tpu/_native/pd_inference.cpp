// C serving ABI (reference: paddle/fluid/inference/capi_exp/pd_inference_api.h
// PD_ConfigCreate/PD_PredictorCreate/PD_PredictorRun/PD_Tensor*, consumed by
// the Go bindings paddle/fluid/inference/goapi/predictor.go).
//
// TPU-native design: the compute path IS XLA — a saved artifact's fast path
// is a StableHLO program executed by the XLA runtime. This shim embeds a
// CPython interpreter that drives the existing predictor stack
// (paddle_tpu.inference.create_predictor), so a non-Python service links ONE
// shared library, calls the same PD_* surface the reference exposes, and the
// heavy lifting still happens inside compiled XLA programs — the interpreter
// only orchestrates (the reference's C API similarly marshals into its C++
// AnalysisPredictor; here the "C++ engine" is XLA itself).
//
// Threading: every entry point takes the GIL via PyGILState; PD_Init
// releases the GIL after bootstrap so callers may invoke from any thread.
// Errors: returns 0/NULL and records a message for PD_GetLastError().

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::mutex g_err_mu;
std::string g_last_error;

void set_error(const std::string& msg) {
  std::lock_guard<std::mutex> l(g_err_mu);
  g_last_error = msg;
}

// capture the pending Python exception into g_last_error
void capture_py_error(const char* where) {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = std::string(where) + ": ";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* u = PyUnicode_AsUTF8(s);
      msg += u ? u : "<error text not utf-8 representable>";
      Py_DECREF(s);
    }
  } else {
    msg += "unknown python error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

struct CConfig {
  std::string model_dir;
};

struct CTensor;

struct CPredictor {
  PyObject* pred = nullptr;                  // paddle predictor object
  // deque: element addresses are stable across growth, so c_str()
  // pointers handed to C callers stay valid for the predictor lifetime
  std::deque<std::string> input_names;
  std::deque<std::string> output_names;
  std::vector<CTensor*> tensors;             // owned handles
  uint64_t run_id = 0;                       // bumps on every Run
};

struct CTensor {
  CPredictor* owner = nullptr;
  std::string name;
  bool is_input = false;
  PyObject* handle = nullptr;                // python Tensor handle
  PyObject* last_out = nullptr;              // cached NATIVE-dtype ndarray
  uint64_t fetched_run = 0;                  // run_id last_out belongs to
  std::vector<int64_t> shape;
};

bool g_we_initialized = false;

std::vector<std::string> names_from_list(PyObject* list) {
  std::vector<std::string> out;
  if (!list) return out;
  Py_ssize_t n = PySequence_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PySequence_GetItem(list, i);
    if (item) {
      const char* s = PyUnicode_AsUTF8(item);
      if (s) out.emplace_back(s);
      Py_DECREF(item);
    }
  }
  return out;
}

// np.frombuffer(memoryview, dtype).reshape(shape).copy()
PyObject* ndarray_from(const void* data, size_t nbytes, const char* dtype,
                       const std::vector<int64_t>& shape) {
  PyObject* np = PyImport_ImportModule("numpy");
  if (!np) return nullptr;
  PyObject* mem = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)), nbytes, PyBUF_READ);
  PyObject* arr = mem ? PyObject_CallMethod(np, "frombuffer", "Os", mem,
                                            dtype)
                      : nullptr;
  Py_XDECREF(mem);
  PyObject* shaped = nullptr;
  if (arr) {
    PyObject* tup = PyTuple_New(shape.size());
    for (size_t i = 0; i < shape.size(); ++i)
      PyTuple_SetItem(tup, i, PyLong_FromLongLong(shape[i]));
    PyObject* reshaped = PyObject_CallMethod(arr, "reshape", "O", tup);
    Py_DECREF(tup);
    if (reshaped) {
      shaped = PyObject_CallMethod(reshaped, "copy", nullptr);
      Py_DECREF(reshaped);
    }
    Py_DECREF(arr);
  }
  Py_DECREF(np);
  return shaped;   // may be null with error set
}

bool copy_from_cpu(CTensor* t, const void* data, const char* dtype,
                   size_t elem) {
  Gil g;
  size_t count = 1;
  for (int64_t d : t->shape) count *= static_cast<size_t>(d);
  PyObject* arr = ndarray_from(data, count * elem, dtype, t->shape);
  if (!arr) {
    capture_py_error("PD_TensorCopyFromCpu");
    return false;
  }
  PyObject* r = PyObject_CallMethod(t->handle, "copy_from_cpu", "O", arr);
  Py_DECREF(arr);
  if (!r) {
    capture_py_error("PD_TensorCopyFromCpu");
    return false;
  }
  Py_DECREF(r);
  return true;
}

// fetch + cache the output ndarray (astype(dtype), C-contiguous).
// The python Predictor REBUILDS its output Tensor objects on every
// run(), so the handle is re-resolved by name here — a C handle held
// across runs must always read the CURRENT run's values.
bool fetch_output(CTensor* t) {
  // per-run cache of the NATIVE-dtype array: GetShape then CopyToCpu
  // (any dtype) transfers the output from the device ONCE per run;
  // dtype conversion happens host-side at copy time
  if (t->last_out && t->fetched_run == t->owner->run_id) {
    return true;
  }
  PyObject* h = PyObject_CallMethod(t->owner->pred, "get_output_handle",
                                    "s", t->name.c_str());
  if (!h) {
    capture_py_error("PD_TensorCopyToCpu(handle)");
    return false;
  }
  PyObject* arr = PyObject_CallMethod(h, "copy_to_cpu", nullptr);
  Py_DECREF(h);
  if (!arr) {
    capture_py_error("PD_TensorCopyToCpu");
    return false;
  }
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* conv =
      np ? PyObject_CallMethod(np, "ascontiguousarray", "O", arr)
         : nullptr;
  Py_XDECREF(np);
  Py_DECREF(arr);
  if (!conv) {
    capture_py_error("PD_TensorCopyToCpu");
    return false;
  }
  Py_XDECREF(t->last_out);
  t->last_out = conv;
  t->fetched_run = t->owner->run_id;
  return true;
}

}  // namespace

extern "C" {

// ---- lifecycle ----

// Initialize the embedded runtime. repo_root (may be NULL) is prepended to
// sys.path so an installed-by-checkout paddle_tpu resolves. Safe to call
// when the host process is already a Python interpreter (the test harness):
// then nothing is initialized and teardown is a no-op.
int PD_Init(const char* repo_root) {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
  }
  {
    Gil g;
    if (repo_root && *repo_root) {
      PyObject* sys_path = PySys_GetObject("path");  // borrowed
      PyObject* p = PyUnicode_FromString(repo_root);
      if (sys_path && p) PyList_Insert(sys_path, 0, p);
      Py_XDECREF(p);
    }
  }
  if (g_we_initialized) {
    // release the GIL the bootstrap holds so any thread can call PD_*
    static PyThreadState* main_state = nullptr;
    if (!main_state) main_state = PyEval_SaveThread();
  }
  return 1;
}

void PD_Finalize() {
  // Embedded XLA runtimes do not tear down cleanly (the same reason
  // __graft_entry__ exits via os._exit); leave the interpreter alive and
  // let process exit reclaim everything, matching the reference's
  // process-lifetime predictor pools.
}

const char* PD_GetLastError() {
  // a per-thread copy: the returned pointer must survive a concurrent
  // set_error reallocating the shared string
  static thread_local std::string tl;
  {
    std::lock_guard<std::mutex> l(g_err_mu);
    tl = g_last_error;
  }
  return tl.c_str();
}

// ---- config ----

void* PD_ConfigCreate() { return new CConfig(); }

void PD_ConfigDestroy(void* cfg) { delete static_cast<CConfig*>(cfg); }

void PD_ConfigSetModelDir(void* cfg, const char* dir) {
  static_cast<CConfig*>(cfg)->model_dir = dir ? dir : "";
}

// ---- predictor ----

void* PD_PredictorCreate(void* cfg_v) {
  auto* cfg = static_cast<CConfig*>(cfg_v);
  Gil g;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (!mod) {
    capture_py_error("PD_PredictorCreate(import)");
    return nullptr;
  }
  PyObject* pycfg = PyObject_CallMethod(mod, "Config", "s",
                                        cfg->model_dir.c_str());
  PyObject* pred =
      pycfg ? PyObject_CallMethod(mod, "create_predictor", "O", pycfg)
            : nullptr;
  Py_XDECREF(pycfg);
  Py_DECREF(mod);
  if (!pred) {
    capture_py_error("PD_PredictorCreate");
    return nullptr;
  }
  auto* p = new CPredictor();
  p->pred = pred;
  PyObject* in = PyObject_CallMethod(pred, "get_input_names", nullptr);
  for (const std::string& n : names_from_list(in)) {
    p->input_names.push_back(n);
  }
  Py_XDECREF(in);
  PyErr_Clear();
  return p;
}

void PD_PredictorDestroy(void* pred_v) {
  auto* p = static_cast<CPredictor*>(pred_v);
  if (!p) return;
  Gil g;
  for (CTensor* t : p->tensors) {
    Py_XDECREF(t->handle);
    Py_XDECREF(t->last_out);
    delete t;
  }
  Py_XDECREF(p->pred);
  delete p;
}

size_t PD_PredictorGetInputNum(void* pred_v) {
  Gil g;   // serialize against concurrent mutation (any-thread contract)
  return static_cast<CPredictor*>(pred_v)->input_names.size();
}

const char* PD_PredictorGetInputName(void* pred_v, size_t i) {
  Gil g;
  auto* p = static_cast<CPredictor*>(pred_v);
  return i < p->input_names.size() ? p->input_names[i].c_str() : "";
}

size_t PD_PredictorGetOutputNum(void* pred_v) {
  Gil g;   // PD_PredictorRun rewrites output_names under the GIL
  return static_cast<CPredictor*>(pred_v)->output_names.size();
}

const char* PD_PredictorGetOutputName(void* pred_v, size_t i) {
  Gil g;
  auto* p = static_cast<CPredictor*>(pred_v);
  return i < p->output_names.size() ? p->output_names[i].c_str() : "";
}

static CTensor* find_handle(CPredictor* p, const char* name, bool input) {
  for (CTensor* t : p->tensors) {
    if (t->is_input == input && t->name == name) return t;
  }
  return nullptr;
}

static void* get_handle(CPredictor* p, const char* name, bool input) {
  // one CTensor per (name, direction): serving loops re-fetch handles
  // every iteration and must not grow the handle table unboundedly.
  // The GIL serializes scan/growth, but a Python call in the middle can
  // YIELD it — so re-scan after the call before publishing.
  Gil g;
  if (CTensor* t = find_handle(p, name, input)) return t;
  auto* t = new CTensor();
  t->owner = p;
  t->name = name;
  t->is_input = input;
  if (input) {
    t->handle = PyObject_CallMethod(p->pred, "get_input_handle", "s",
                                    name);
    if (!t->handle) {
      capture_py_error("PD_PredictorGetInputHandle");
      delete t;
      return nullptr;
    }
    // the call above may have yielded the GIL: a racing thread could
    // have inserted this handle — keep THEIRS, discard ours
    if (CTensor* existing = find_handle(p, name, input)) {
      Py_XDECREF(t->handle);
      delete t;
      return existing;
    }
  }
  // outputs: no cached python handle — the predictor rebuilds output
  // tensors on every run, so they resolve by name at read time
  p->tensors.push_back(t);
  return t;
}

void* PD_PredictorGetInputHandle(void* pred_v, const char* name) {
  return get_handle(static_cast<CPredictor*>(pred_v), name, true);
}

void* PD_PredictorGetOutputHandle(void* pred_v, const char* name) {
  return get_handle(static_cast<CPredictor*>(pred_v), name, false);
}

int PD_PredictorRun(void* pred_v) {
  auto* p = static_cast<CPredictor*>(pred_v);
  Gil g;
  PyObject* r = PyObject_CallMethod(p->pred, "run", nullptr);
  if (!r) {
    capture_py_error("PD_PredictorRun");
    return 0;
  }
  Py_DECREF(r);
  // bump AFTER run() returns: the call yields the GIL at bytecode
  // boundaries, and a concurrent fetch mid-run must not cache the
  // previous run's output under the new id
  p->run_id++;
  PyObject* out = PyObject_CallMethod(p->pred, "get_output_names", nullptr);
  // append-only merge: returned name pointers (GetOutputName) must stay
  // valid for the predictor's lifetime — never free or reassign entries
  for (const std::string& n : names_from_list(out)) {
    bool have = false;
    for (const std::string& e : p->output_names) {
      if (e == n) {
        have = true;
        break;
      }
    }
    if (!have) p->output_names.push_back(n);
  }
  Py_XDECREF(out);
  PyErr_Clear();
  return 1;
}

// ---- tensors ----

void PD_TensorReshape(void* t_v, int ndim, const int64_t* shape) {
  auto* t = static_cast<CTensor*>(t_v);
  t->shape.assign(shape, shape + ndim);
}

int PD_TensorCopyFromCpuFloat(void* t_v, const float* data) {
  return copy_from_cpu(static_cast<CTensor*>(t_v), data, "float32", 4);
}

int PD_TensorCopyFromCpuInt32(void* t_v, const int32_t* data) {
  return copy_from_cpu(static_cast<CTensor*>(t_v), data, "int32", 4);
}

int PD_TensorCopyFromCpuInt64(void* t_v, const int64_t* data) {
  return copy_from_cpu(static_cast<CTensor*>(t_v), data, "int64", 8);
}

// ndim via return; shape written into caller buffer (cap entries).
// Inputs report the staged PD_TensorReshape shape (the inference
// Tensor's python `shape` is a method, not an attribute); outputs
// report the CURRENT run's ndarray shape.
int PD_TensorGetShape(void* t_v, int64_t* shape, int cap) {
  auto* t = static_cast<CTensor*>(t_v);
  if (t->is_input) {
    int n = static_cast<int>(t->shape.size());
    for (int i = 0; i < n && i < cap; ++i) shape[i] = t->shape[i];
    return n;
  }
  Gil g;
  if (!fetch_output(t)) return -1;   // native dtype: shape-only read
  PyObject* shp = PyObject_GetAttrString(t->last_out, "shape");
  if (!shp) {
    capture_py_error("PD_TensorGetShape");
    return -1;
  }
  Py_ssize_t n = PySequence_Size(shp);
  for (Py_ssize_t i = 0; i < n && i < cap; ++i) {
    PyObject* d = PySequence_GetItem(shp, i);
    shape[i] = d ? PyLong_AsLongLong(d) : -1;
    Py_XDECREF(d);
  }
  Py_DECREF(shp);
  PyErr_Clear();
  return static_cast<int>(n);
}

static int copy_to_cpu(CTensor* t, void* out, const char* dtype) {
  Gil g;
  if (!fetch_output(t)) return 0;
  // host-side dtype conversion from the cached native array (no second
  // device transfer)
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* conv =
      np ? PyObject_CallMethod(np, "ascontiguousarray", "Os",
                               t->last_out, dtype)
         : nullptr;
  Py_XDECREF(np);
  if (!conv) {
    capture_py_error("PD_TensorCopyToCpu");
    return 0;
  }
  PyObject* b = PyObject_CallMethod(conv, "tobytes", nullptr);
  Py_DECREF(conv);
  if (!b) {
    capture_py_error("PD_TensorCopyToCpu");
    return 0;
  }
  char* buf = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(b, &buf, &n) == 0) {
    std::memcpy(out, buf, static_cast<size_t>(n));
  }
  Py_DECREF(b);
  return 1;
}

int PD_TensorCopyToCpuFloat(void* t_v, float* out) {
  return copy_to_cpu(static_cast<CTensor*>(t_v), out, "float32");
}

int PD_TensorCopyToCpuInt32(void* t_v, int32_t* out) {
  return copy_to_cpu(static_cast<CTensor*>(t_v), out, "int32");
}

int PD_TensorCopyToCpuInt64(void* t_v, int64_t* out) {
  return copy_to_cpu(static_cast<CTensor*>(t_v), out, "int64");
}

}  // extern "C"
