// Native SSD-tier sparse table (reference:
// paddle/fluid/distributed/ps/table/ssd_sparse_table.h — RocksDB-backed
// rows behind a RAM hot cache; the reference's table storage layer is
// C++, so this framework's is too).
//
// Design (matches the python SSDTable contract in
// distributed/ps/the_one_ps.py): fixed-size records (row + adagrad
// accumulator, 2*dim float32) in one slot file addressed by a RAM
// key->slot index; bounded LRU cache of hot rows; evictions write back.
// Row INITIALIZATION stays in python (numpy PCG64 stream parity): pull
// reports missing keys, the wrapper inserts initialized rows.
//
// Exposed C ABI (ctypes): pt_ssd_open/pull/insert/push/flush/stats/close.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <list>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kRecGrow = 65536;  // slots per file extension

struct Entry {
  std::vector<float> row;
  std::vector<float> g2;
  std::list<int64_t>::iterator it;  // position in LRU order
};

struct SsdTable {
  int fd = -1;
  int64_t dim = 0;
  int64_t rec = 0;  // record bytes: 2*dim*4
  int64_t capacity = 0;  // slots allocated in the file
  size_t cache_rows = 0;
  int64_t evictions = 0;
  bool io_error = false;  // sticky: any slot read/write failure
  std::unordered_map<int64_t, int64_t> slots;  // key -> slot
  std::list<int64_t> order;                    // LRU (front = oldest)
  std::unordered_map<int64_t, Entry> cache;
  std::mutex mu;
};

bool ensure_capacity(SsdTable* t, int64_t slot) {
  if (slot < t->capacity) return true;
  int64_t cap = t->capacity;
  while (slot >= cap) cap += kRecGrow;
  if (ftruncate(t->fd, cap * t->rec) != 0) return false;
  t->capacity = cap;
  return true;
}

bool write_slot(SsdTable* t, int64_t slot, const float* row,
                const float* g2) {
  if (!ensure_capacity(t, slot)) return false;
  const int64_t half = t->dim * (int64_t)sizeof(float);
  if (pwrite(t->fd, row, half, slot * t->rec) != half) return false;
  if (pwrite(t->fd, g2, half, slot * t->rec + half) != half) return false;
  return true;
}

bool read_slot(SsdTable* t, int64_t slot, float* row, float* g2) {
  const int64_t half = t->dim * (int64_t)sizeof(float);
  if (pread(t->fd, row, half, slot * t->rec) != half) return false;
  if (pread(t->fd, g2, half, slot * t->rec + half) != half) return false;
  return true;
}

void evict_if_full(SsdTable* t) {
  while (t->cache.size() > t->cache_rows && !t->order.empty()) {
    int64_t k = t->order.front();
    t->order.pop_front();
    auto it = t->cache.find(k);
    if (it == t->cache.end()) continue;
    if (!write_slot(t, t->slots[k], it->second.row.data(),
                    it->second.g2.data()))
      t->io_error = true;  // losing an evicted row silently would
                           // corrupt training state — fail the table
    t->cache.erase(it);
    t->evictions++;
  }
}

void touch(SsdTable* t, std::unordered_map<int64_t, Entry>::iterator it,
           int64_t key) {
  t->order.erase(it->second.it);
  t->order.push_back(key);
  it->second.it = std::prev(t->order.end());
}

// cache-or-disk lookup. status: 0 = found (*out set), 1 = key absent,
// -1 = I/O failure (a disk error must NOT read as "missing" — the
// wrapper would silently re-initialize a trained row).
int get_entry(SsdTable* t, int64_t key, Entry** out) {
  auto it = t->cache.find(key);
  if (it != t->cache.end()) {
    touch(t, it, key);
    *out = &it->second;
    return 0;
  }
  auto sit = t->slots.find(key);
  if (sit == t->slots.end()) return 1;
  Entry e;
  e.row.resize(t->dim);
  e.g2.resize(t->dim);
  if (!read_slot(t, sit->second, e.row.data(), e.g2.data())) {
    t->io_error = true;
    return -1;
  }
  t->order.push_back(key);
  e.it = std::prev(t->order.end());
  t->cache.emplace(key, std::move(e));
  evict_if_full(t);
  // eviction cannot remove the entry just appended at the LRU back
  // unless cache_rows == 0; re-find to stay correct in that edge
  auto again = t->cache.find(key);
  if (again == t->cache.end()) return -1;
  *out = &again->second;
  return 0;
}

}  // namespace

extern "C" {

void* pt_ssd_open(const char* path, int64_t dim, int64_t cache_rows) {
  SsdTable* t = new SsdTable();
  t->dim = dim;
  t->rec = 2 * dim * (int64_t)sizeof(float);
  t->cache_rows = (size_t)(cache_rows > 0 ? cache_rows : 1);
  t->fd = open(path, O_RDWR | O_CREAT, 0644);
  if (t->fd < 0) {
    delete t;
    return nullptr;
  }
  return t;
}

// out: (n, dim) float32. missing: caller-allocated int64[n]; returns the
// count of missing keys written there (their out rows are untouched),
// or -1 on I/O failure.
int64_t pt_ssd_pull(void* h, const int64_t* keys, int64_t n, float* out,
                    int64_t* missing) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  int64_t miss = 0;
  for (int64_t i = 0; i < n; ++i) {
    Entry* e = nullptr;
    int st = get_entry(t, keys[i], &e);
    if (st < 0 || t->io_error) return -1;
    if (st == 1) {
      missing[miss++] = i;
      continue;
    }
    memcpy(out + i * t->dim, e->row.data(), t->dim * sizeof(float));
  }
  return miss;
}

// rows: (n, dim) initialized values for NEW keys (g2 starts zero).
int pt_ssd_insert(void* h, const int64_t* keys, int64_t n,
                  const float* rows) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t key = keys[i];
    if (t->slots.find(key) == t->slots.end())
      t->slots.emplace(key, (int64_t)t->slots.size());
    auto it = t->cache.find(key);
    if (it != t->cache.end()) {
      memcpy(it->second.row.data(), rows + i * t->dim,
             t->dim * sizeof(float));
      std::fill(it->second.g2.begin(), it->second.g2.end(), 0.f);
      touch(t, it, key);
      continue;
    }
    Entry e;
    e.row.assign(rows + i * t->dim, rows + (i + 1) * t->dim);
    e.g2.assign(t->dim, 0.f);
    t->order.push_back(key);
    e.it = std::prev(t->order.end());
    t->cache.emplace(key, std::move(e));
    evict_if_full(t);
  }
  return 0;
}

// opt: 0 = sgd, 1 = adagrad. Unknown keys are skipped; their INDICES
// land in caller-allocated skipped[n] and the count is returned (the
// wrapper initializes exactly those and re-pushes only them — re-pushing
// the whole batch would double-apply existing keys). -1 on I/O failure.
int64_t pt_ssd_push(void* h, const int64_t* keys, int64_t n,
                    const float* grads, float lr, int opt,
                    int64_t* skipped) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  int64_t n_skip = 0;
  for (int64_t i = 0; i < n; ++i) {
    Entry* e = nullptr;
    int st = get_entry(t, keys[i], &e);
    if (st < 0 || t->io_error) return -1;
    if (st == 1) {
      skipped[n_skip++] = i;
      continue;
    }
    const float* g = grads + i * t->dim;
    float* row = e->row.data();
    float* g2 = e->g2.data();
    if (opt == 1) {
      for (int64_t d = 0; d < t->dim; ++d) {
        g2[d] += g[d] * g[d];
        row[d] -= lr * g[d] / (sqrtf(g2[d]) + 1e-8f);
      }
    } else {
      for (int64_t d = 0; d < t->dim; ++d) row[d] -= lr * g[d];
    }
  }
  return n_skip;
}

int pt_ssd_flush(void* h) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  for (auto& kv : t->cache) {
    if (!write_slot(t, t->slots[kv.first], kv.second.row.data(),
                    kv.second.g2.data()))
      return -1;
  }
  return fsync(t->fd) == 0 ? 0 : -1;
}

// out: int64[4] = {keys, ram_rows, evictions, disk_bytes}
int pt_ssd_stats(void* h, int64_t* out) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  struct stat st;
  out[0] = (int64_t)t->slots.size();
  out[1] = (int64_t)t->cache.size();
  out[2] = t->evictions;
  out[3] = fstat(t->fd, &st) == 0 ? (int64_t)st.st_size : 0;
  return 0;
}

// Bulk export for table checkpointing (reference: ssd_sparse_table.h
// Save — the PS persists its shards). Writes every (key, row, g2)
// triple; caller sizes the buffers from stats[0]. Cache is flushed
// first so slot data is fresh. Returns the key count, -1 on I/O error.
int64_t pt_ssd_dump(void* h, int64_t* keys, float* rows, float* g2) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  for (auto& kv : t->cache) {
    if (!write_slot(t, t->slots[kv.first], kv.second.row.data(),
                    kv.second.g2.data()))
      return -1;
  }
  int64_t i = 0;
  for (auto& kv : t->slots) {
    keys[i] = kv.first;
    if (!read_slot(t, kv.second, rows + i * t->dim, g2 + i * t->dim))
      return -1;
    ++i;
  }
  return i;
}

// Bulk import (checkpoint load): assigns slots in order, writes rows+g2
// straight to disk, and drops the RAM cache (stale pre-load entries must
// not shadow restored values). 0 on success, -1 on I/O error.
int pt_ssd_restore(void* h, const int64_t* keys, int64_t n,
                   const float* rows, const float* g2) {
  SsdTable* t = (SsdTable*)h;
  std::lock_guard<std::mutex> lock(t->mu);
  // the checkpoint is authoritative: keys trained after the save must
  // NOT survive the restore (RAM-table load clears; so does this).
  // Orphaned slot payloads beyond the new index are unreferenced.
  t->cache.clear();
  t->order.clear();
  t->slots.clear();
  for (int64_t i = 0; i < n; ++i) {
    t->slots.emplace(keys[i], i);
    if (!write_slot(t, i, rows + i * t->dim, g2 + i * t->dim)) return -1;
  }
  return fsync(t->fd) == 0 ? 0 : -1;
}

void pt_ssd_close(void* h) {
  SsdTable* t = (SsdTable*)h;
  if (t == nullptr) return;
  pt_ssd_flush(h);
  close(t->fd);
  delete t;
}

}  // extern "C"
