// Native host event tracer — the HostTracer analog.
//
// Re-design of the reference's native profiler collection path
// (reference: paddle/fluid/platform/profiler/host_tracer.cc — RecordEvent
// spans land in a native buffer without touching the Python allocator or
// GIL-serialized list appends; the chrome-trace writer reads them out).
//
// Fixed-record ring: the hot path (pt_trace_record) takes one mutex'd
// append of 32 bytes — called from any thread, including DataLoader
// workers and the step timer. Python interns names to int32 ids and
// rebuilds strings at dump time.
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

extern "C" {

struct PtTraceEvent {
  int64_t start_ns;
  int64_t end_ns;
  int64_t tid;
  int32_t name_id;
  int32_t type_id;
};

static std::vector<PtTraceEvent> g_events;
static std::mutex g_mu;
static bool g_enabled = false;
static size_t g_capacity = 0;
static int64_t g_dropped = 0;

void pt_trace_enable(int64_t capacity) {
  std::lock_guard<std::mutex> lk(g_mu);
  g_capacity = capacity > 0 ? static_cast<size_t>(capacity) : (1u << 20);
  g_events.clear();
  g_events.reserve(g_capacity < (1u << 16) ? g_capacity : (1u << 16));
  g_dropped = 0;
  g_enabled = true;
}

void pt_trace_disable() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_enabled = false;
}

int pt_trace_record(int32_t name_id, int32_t type_id, int64_t start_ns,
                    int64_t end_ns, int64_t tid) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (!g_enabled) return 0;
  if (g_events.size() >= g_capacity) {  // bounded: drop, count, report
    ++g_dropped;
    return -1;
  }
  g_events.push_back(PtTraceEvent{start_ns, end_ns, tid, name_id, type_id});
  return 1;
}

int64_t pt_trace_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  return static_cast<int64_t>(g_events.size());
}

int64_t pt_trace_dropped() {
  std::lock_guard<std::mutex> lk(g_mu);
  return g_dropped;
}

// copy up to max events into out; returns the number copied
int64_t pt_trace_dump(PtTraceEvent* out, int64_t max) {
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t n = static_cast<int64_t>(g_events.size());
  if (n > max) n = max;
  std::memcpy(out, g_events.data(),
              static_cast<size_t>(n) * sizeof(PtTraceEvent));
  return n;
}

// copy AND remove up to max events atomically (spans recorded while the
// reader was busy stay queued for the next drain — no dump/clear gap)
int64_t pt_trace_drain(PtTraceEvent* out, int64_t max) {
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t n = static_cast<int64_t>(g_events.size());
  if (n > max) n = max;
  std::memcpy(out, g_events.data(),
              static_cast<size_t>(n) * sizeof(PtTraceEvent));
  g_events.erase(g_events.begin(), g_events.begin() + n);
  return n;
}

void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  g_events.clear();
  g_dropped = 0;
}

}  // extern "C"
