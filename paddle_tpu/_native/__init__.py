"""Native (C++) runtime components, built in-tree with g++ at first use.

Where the reference is native, this framework is native too (SURVEY §2.1
directive): the TCP coordination store (reference:
paddle/phi/core/distributed/store/tcp_store.h:121) and the host data path
(reference: paddle/fluid/framework/data_feed.cc) are C++ with ctypes
bindings (pybind11 is not in this image). Build artifacts cache next to
the sources; a pure-Python fallback keeps the framework importable on
toolchain-less machines.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["store.cpp", "datapath.cpp", "ckptio.cpp", "datafeed.cpp",
            "hosttracer.cpp", "ssdtable.cpp"]
_lock = threading.Lock()
_lib = None
_build_error = None


def _src_hash() -> str:
    h = hashlib.sha256()
    for s in _SOURCES:
        with open(os.path.join(_DIR, s), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _compile(srcs, out, extra_flags=()) -> str:
    """Compile-and-cache: skip when the hashed artifact exists; build to
    a pid-unique temp so concurrent builders (pytest-xdist, two services
    cold-starting) can't interleave output, then atomically publish."""
    if os.path.exists(out):
        return out
    tmp = f"{out}.tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *srcs, "-o", tmp, *extra_flags]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, out)
    return out


def _build() -> str:
    return _compile(
        [os.path.join(_DIR, s) for s in _SOURCES],
        os.path.join(_DIR, f"libpaddle_tpu_native_{_src_hash()}.so"))


def build_capi() -> str:
    """Build (cached) the C serving ABI shared library
    (pd_inference.cpp — reference capi_exp/pd_inference_api.h). Linked
    against libpython: the shim embeds an interpreter that drives the
    XLA predictor; non-Python services link only this library."""
    import sysconfig
    src = os.path.join(_DIR, "pd_inference.cpp")
    with open(src, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:16]
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = (sysconfig.get_config_var("LDVERSION")
           or sysconfig.get_config_var("VERSION"))
    return _compile(
        [src], os.path.join(_DIR, f"libpaddle_tpu_capi_{h}.so"),
        extra_flags=[f"-I{inc}", f"-L{libdir}", f"-lpython{ver}",
                     f"-Wl,-rpath,{libdir}"])


def load():
    """Build (cached) + dlopen the native library; None if unavailable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            lib = ctypes.CDLL(_build())
        except Exception as e:  # no g++ / sandboxed build failure
            _build_error = e
            return None
        # ---- signatures ----
        lib.pt_store_server_start.restype = ctypes.c_void_p
        lib.pt_store_server_start.argtypes = [ctypes.c_int]
        lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.pt_store_client_connect.restype = ctypes.c_int
        lib.pt_store_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.pt_store_client_close.argtypes = [ctypes.c_int]
        lib.pt_store_request.restype = ctypes.c_int
        lib.pt_store_request.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int)]
        lib.pt_store_free.argtypes = [ctypes.c_void_p]
        lib.pt_collate.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
        lib.pt_shuffle_indices.argtypes = [
            ctypes.c_int64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.pt_normalize_nhwc_to_nchw.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        lib.pt_file_write.restype = ctypes.c_longlong
        lib.pt_file_write.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_int]
        lib.pt_file_read.restype = ctypes.c_longlong
        lib.pt_file_read.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_longlong,
            ctypes.c_int]
        lib.pt_trace_enable.argtypes = [ctypes.c_int64]
        lib.pt_trace_disable.argtypes = []
        lib.pt_trace_record.restype = ctypes.c_int
        lib.pt_trace_record.argtypes = [
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64]
        lib.pt_trace_count.restype = ctypes.c_int64
        lib.pt_trace_dropped.restype = ctypes.c_int64
        lib.pt_trace_dump.restype = ctypes.c_int64
        lib.pt_trace_dump.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pt_trace_drain.restype = ctypes.c_int64
        lib.pt_trace_drain.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pt_trace_clear.argtypes = []
        lib.pt_ssd_open.restype = ctypes.c_void_p
        lib.pt_ssd_open.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64]
        lib.pt_ssd_pull.restype = ctypes.c_int64
        lib.pt_ssd_pull.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64)]
        lib.pt_ssd_insert.restype = ctypes.c_int
        lib.pt_ssd_insert.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float)]
        lib.pt_ssd_push.restype = ctypes.c_int64
        lib.pt_ssd_push.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.c_float, ctypes.c_int, ctypes.POINTER(ctypes.c_int64)]
        lib.pt_ssd_flush.restype = ctypes.c_int
        lib.pt_ssd_flush.argtypes = [ctypes.c_void_p]
        lib.pt_ssd_stats.restype = ctypes.c_int
        lib.pt_ssd_stats.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64)]
        lib.pt_ssd_dump.restype = ctypes.c_int64
        lib.pt_ssd_dump.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float)]
        lib.pt_ssd_restore.restype = ctypes.c_int
        lib.pt_ssd_restore.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float)]
        lib.pt_ssd_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None
