// Native MultiSlot datafeed parser — the hot loop of the PS/fleet slot
// pipeline (reference: paddle/fluid/framework/data_feed.cc
// MultiSlotDataFeed::ParseOneInstance — C++ trainer-thread parsing).
//
// Parses "<n> v1 ... vn" repeated per slot per line into per-slot
// columns. The whole file parse runs WITHOUT the GIL (called via ctypes)
// and multi-threads across line ranges.
//
// Protocol (two-pass, caller allocates):
//   pass 1: pt_slotfile_scan  -> counts (n_samples, per-slot total values)
//   pass 2: pt_slotfile_parse -> fills values + per-sample lengths
#include <atomic>
#include <charconv>
#include <cerrno>
#include <clocale>
#include <locale.h>
#include <cctype>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Number parsing with python-float()/int() compatibility AND locale
// independence: strtod_l/strtol_l against a process-wide "C" locale
// (python's float() is itself a C-locale strtod-equivalent: leading '+'
// accepted, overflow saturates to +/-inf, underflow to 0). The token is
// bounded-copied so parsing can never run past this line.
static locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

static const char* token_end(const char* p, const char* end) {
  const char* q = p;
  while (q < end && *q != ' ' && *q != '\t' && *q != '\r' && *q != '\n')
    ++q;
  return q;
}

// exotic forms BOTH paths reject by contract (documented in
// dataset.py): hex floats ('0x10' — strtod accepts, float() rejects)
// and PEP-515 underscores ('1_5' — float() accepts, strtod rejects).
// Rejecting them on both sides keeps the paths sample-identical.
static bool exotic_token(const char* p, size_t n) {
  for (size_t i = 0; i < n; ++i)
    // '(' also rejects C99 "nan(n-char-seq)" which strtod accepts but
    // python float() does not
    if (p[i] == '_' || p[i] == 'x' || p[i] == 'X' || p[i] == '(')
      return true;
  return false;
}

static const char* parse_double_py(const char* p, const char* end,
                                   double* out) {
  const char* te = token_end(p, end);
  size_t n = static_cast<size_t>(te - p);
  if (n == 0 || exotic_token(p, n)) return nullptr;
  char buf[64];
  char* ep = nullptr;
  if (n < sizeof(buf)) {
    memcpy(buf, p, n);
    buf[n] = '\0';
    *out = strtod_l(buf, &ep, c_locale());
    if (ep != buf + n) return nullptr;  // trailing junk in the token
  } else {
    // pathological long token (excess precision/padding): heap copy —
    // the python fallback parses these, so must we
    std::string big(p, n);
    *out = strtod_l(big.c_str(), &ep, c_locale());
    if (ep != big.c_str() + n) return nullptr;
  }
  return te;
}

static const char* parse_long_py(const char* p, const char* end,
                                 long* out) {
  const char* te = token_end(p, end);
  size_t n = static_cast<size_t>(te - p);
  if (n == 0 || exotic_token(p, n)) return nullptr;
  char buf[32];
  char* ep = nullptr;
  if (n < sizeof(buf)) {
    memcpy(buf, p, n);
    buf[n] = '\0';
    errno = 0;
    *out = strtol_l(buf, &ep, 10, c_locale());
    if (ep != buf + n || errno == ERANGE) return nullptr;
  } else {
    // zero-padded/pathological long count token: python int() parses it
    std::string big(p, n);
    errno = 0;
    *out = strtol_l(big.c_str(), &ep, 10, c_locale());
    if (ep != big.c_str() + n || errno == ERANGE) return nullptr;
  }
  return te;
}

struct Line {
  const char* begin;
  const char* end;
};

// split buffer into non-empty lines
static std::vector<Line> split_lines(const char* buf, int64_t len) {
  std::vector<Line> lines;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* stop = nl ? nl : end;
    const char* q = p;
    while (q < stop && isspace(static_cast<unsigned char>(*q))) ++q;
    if (q < stop) lines.push_back({p, stop});
    p = stop + 1;
  }
  return lines;
}

// parse one line: for each slot read count then values; returns false on
// malformed input (caller skips the line, like the python fallback)
static bool parse_line(const Line& ln, int n_slots, double* vals_out,
                       int64_t* counts_out, int64_t max_vals,
                       int64_t* n_vals) {
  const char* p = ln.begin;
  const char* end = ln.end;
  int64_t written = 0;
  for (int s = 0; s < n_slots; ++s) {
    // manual in-line whitespace skip (never walks through '\n' into the
    // next line on a truncated slot list)
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
    if (p >= end) return false;
    // strtol_l/strtod_l with the cached "C" locale: locale-INDEPENDENT
    // (plain strtol/strtod would honor LC_NUMERIC and diverge from the
    // python fallback under e.g. de_DE)
    long cnt = 0;
    const char* next = parse_long_py(p, end, &cnt);
    if (next == nullptr || cnt < 0) return false;  // "1.5" etc. rejected
    p = next;
    for (long i = 0; i < cnt; ++i) {
      while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
      if (p >= end) return false;
      double v = 0.0;
      const char* vnext = parse_double_py(p, end, &v);
      if (vnext == nullptr) return false;
      p = vnext;
      if (vals_out) {
        if (written >= max_vals) return false;
        vals_out[written] = v;
      }
      ++written;
    }
    if (counts_out) counts_out[s] = cnt;
    if (p > end) return false;
  }
  *n_vals = written;
  return true;
}

}  // namespace

extern "C" {

// Pass 1: count well-formed samples and total values (all slots).
// Returns n_samples; total_vals receives the value count.
int64_t pt_slotfile_scan(const char* buf, int64_t len, int n_slots,
                         int64_t* total_vals, int num_threads) {
  auto lines = split_lines(buf, len);
  std::atomic<int64_t> samples{0}, vals{0};
  auto work = [&](size_t lo, size_t hi) {
    int64_t local_s = 0, local_v = 0;
    for (size_t i = lo; i < hi; ++i) {
      int64_t nv = 0;
      if (parse_line(lines[i], n_slots, nullptr, nullptr, 0, &nv)) {
        ++local_s;
        local_v += nv;
      }
    }
    samples += local_s;
    vals += local_v;
  };
  int nt = num_threads > 1 ? num_threads : 1;
  if (nt == 1 || lines.size() < 64) {
    work(0, lines.size());
  } else {
    std::vector<std::thread> ts;
    size_t per = (lines.size() + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      size_t lo = t * per;
      size_t hi = lo + per < lines.size() ? lo + per : lines.size();
      if (lo >= hi) break;
      ts.emplace_back(work, lo, hi);
    }
    for (auto& t : ts) t.join();
  }
  *total_vals = vals.load();
  return samples.load();
}

// Pass 2: parse into caller-allocated buffers.
//   values:  double[total_vals]   (slot-major within each sample)
//   lengths: int64[n_samples * n_slots]  per-sample per-slot counts
// Single-threaded fill (deterministic order); parsing already validated.
int64_t pt_slotfile_parse(const char* buf, int64_t len, int n_slots,
                          double* values, int64_t total_vals,
                          int64_t* lengths, int64_t n_samples) {
  auto lines = split_lines(buf, len);
  int64_t si = 0, off = 0;
  std::vector<int64_t> counts(static_cast<size_t>(n_slots));
  for (auto& ln : lines) {
    if (si >= n_samples) break;
    int64_t nv = 0;
    if (!parse_line(ln, n_slots, values + off, counts.data(),
                    total_vals - off, &nv))
      continue;
    memcpy(lengths + si * n_slots, counts.data(),
           sizeof(int64_t) * static_cast<size_t>(n_slots));
    off += nv;
    ++si;
  }
  return si;
}

}  // extern "C"
