"""paddle.signal parity (reference: python/paddle/signal.py — stft/istft
over the fft kernels)."""
from __future__ import annotations

import jax.numpy as jnp

from ._core.autograd import apply
from .ops._registry import as_tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Overlapping frames (reference: signal.py frame). axis=-1: signal on
    the last dim -> (..., frame_length, num_frames); axis=0: signal on the
    first dim -> (num_frames, frame_length, ...)."""
    if axis not in (0, -1):
        raise ValueError(f"axis must be 0 or -1, got {axis}")
    x = as_tensor(x)

    def f(v):
        if axis == 0:
            v = jnp.moveaxis(v, 0, -1)
        n = v.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        out = v[..., idx]                      # (..., num, frame_length)
        if axis == 0:
            # (num, frame_length, ...)
            return jnp.moveaxis(out, (-2, -1), (0, 1))
        return jnp.moveaxis(out, -2, -1)       # (..., frame_length, num)
    return apply(f, x, name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """reference: signal.py overlap_add. axis=-1: (..., frame_length,
    num_frames) -> (..., T); axis=0: (num_frames, frame_length, ...) ->
    (T, ...)."""
    if axis not in (0, -1):
        raise ValueError(f"axis must be 0 or -1, got {axis}")
    x = as_tensor(x)

    def f(v):
        if axis == 0:
            v = jnp.moveaxis(v, (0, 1), (-1, -2))
        fl, num = v.shape[-2], v.shape[-1]
        n = fl + hop_length * (num - 1)
        out = jnp.zeros(v.shape[:-2] + (n,), v.dtype)
        for i in range(num):                  # static unroll (num is small)
            out = out.at[..., i * hop_length:i * hop_length + fl].add(
                v[..., i])
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out
    return apply(f, x, name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """reference: python/paddle/signal.py stft. x: (B, T) or (T,).
    Returns (B, n_fft//2+1, num_frames) complex (onesided)."""
    x = as_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = as_tensor(window)

    def f(v, *rest):
        w = rest[0] if rest else jnp.ones((win_length,), v.dtype)
        if win_length < n_fft:
            pad = (n_fft - win_length) // 2
            w = jnp.pad(w, (pad, n_fft - win_length - pad))
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if center:
            v = jnp.pad(v, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        n = v.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        frames = v[:, idx] * w                 # (B, num, n_fft)
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.moveaxis(spec, 1, 2)         # (B, freq, num)
        return out[0] if squeeze else out

    args = [x] + ([window] if window is not None else [])
    return apply(f, *args, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    x = as_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = as_tensor(window)

    def f(v, *rest):
        w = rest[0] if rest else jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            pad = (n_fft - win_length) // 2
            w = jnp.pad(w, (pad, n_fft - win_length - pad))
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        spec = jnp.moveaxis(v, 1, 2)           # (B, num, freq)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
            else jnp.fft.ifft(spec, axis=-1).real
        frames = frames * w
        num = frames.shape[1]
        n = n_fft + hop_length * (num - 1)
        out = jnp.zeros((frames.shape[0], n), frames.dtype)
        norm = jnp.zeros((n,), frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[:, sl].add(frames[:, i])
            norm = norm.at[sl].add(w * w)
        out = out / jnp.where(norm > 1e-11, norm, 1.0)
        if center:
            out = out[:, n_fft // 2: n - n_fft // 2]
        if length is not None:
            if out.shape[1] < length:  # pad the uncovered tail with zeros
                out = jnp.pad(out, ((0, 0), (0, length - out.shape[1])))
            out = out[:, :length]
        return out[0] if squeeze else out

    args = [x] + ([window] if window is not None else [])
    return apply(f, *args, name="istft")
