"""Vision datasets (reference: python/paddle/vision/datasets/ — cifar.py,
mnist.py, flowers.py...).

Zero-egress environment: datasets load from local files when present
(standard binary layouts), and every dataset supports ``mode='synthetic'``
generating deterministic fake data with the real shapes — that's what tests
and benchmarks use (analog of the reference's test fakes, SURVEY §4).
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset


class _SyntheticImages(Dataset):
    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        self.n = n
        self.shape = shape
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self.images = rng.randint(0, 256, (n,) + shape).astype(np.uint8)
        self.labels = rng.randint(0, num_classes, (n,)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.n


class Cifar10(Dataset):
    """reference: python/paddle/vision/datasets/cifar.py Cifar10."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        assert mode in ("train", "test", "synthetic")
        self.mode = mode
        self.transform = transform
        if mode == "synthetic" or (data_file is None or
                                   not os.path.exists(data_file)):
            if mode != "synthetic" and data_file is not None:
                raise FileNotFoundError(
                    f"{data_file} not found and download is impossible "
                    "(zero-egress); pass mode='synthetic' for fake data")
            syn = _SyntheticImages(50000 if mode == "train" else 10000,
                                   (3, 32, 32), 10,
                                   seed=0 if mode == "train" else 1)
            self.data = [(syn.images[i].reshape(-1), syn.labels[i])
                         for i in range(len(syn))]
        else:
            self.data = []
            with tarfile.open(data_file, mode="r") as f:
                names = [n for n in f.getnames()
                         if ("data_batch" in n if mode == "train"
                             else "test_batch" in n)]
                for name in names:
                    batch = pickle.load(f.extractfile(name),
                                        encoding="bytes")
                    for x, y in zip(batch[b"data"], batch[b"labels"]):
                        self.data.append((x, int(y)))

    def __getitem__(self, idx):
        image, label = self.data[idx]
        image = np.reshape(image, [3, 32, 32]).astype(np.float32)
        if self.transform is not None:
            image = self.transform(image)
        return image, np.asarray(label, np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    pass


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            syn = _SyntheticImages(60000 if mode == "train" else 10000,
                                   (1, 28, 28), 10,
                                   seed=2 if mode == "train" else 3)
            self.images = syn.images
            self.labels = syn.labels
        else:
            with gzip.open(image_path, "rb") as f:
                buf = f.read()
                n = int.from_bytes(buf[4:8], "big")
                self.images = np.frombuffer(
                    buf, np.uint8, offset=16).reshape(n, 1, 28, 28)
            with gzip.open(label_path, "rb") as f:
                buf = f.read()
                self.labels = np.frombuffer(buf, np.uint8,
                                            offset=8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class DatasetFolder(Dataset):
    """reference: python/paddle/vision/datasets/folder.py."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(extensions):
                    self.samples.append((os.path.join(cdir, fn),
                                         self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise RuntimeError("PIL not available; use .npy images")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        extensions = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        self.samples = [os.path.join(root, fn)
                        for fn in sorted(os.listdir(root))
                        if fn.lower().endswith(extensions)]
        self.loader = loader or DatasetFolder._default_loader

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
