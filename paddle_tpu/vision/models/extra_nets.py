"""Remaining zoo families: SqueezeNet, DenseNet, ShuffleNetV2, MobileNetV3,
GoogLeNet, InceptionV3 (reference: python/paddle/vision/models/
{squeezenet,densenet,shufflenetv2,mobilenetv3,googlenet,inceptionv3}.py).

Standard architectures written against this framework's nn surface (NCHW);
XLA lowers the conv/BN stacks onto the MXU.
"""
from __future__ import annotations

from ... import nn
from ...import ops as paddle_ops


def _no_pretrained(pretrained, name):
    if pretrained:
        raise RuntimeError(
            f"pretrained weights for {name} are not bundled in this "
            "framework build; construct the model and load a state_dict")


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k=3, stride=1, padding=None, groups=1,
                 act="relu"):
        super().__init__()
        padding = (k - 1) // 2 if padding is None else padding
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU(), "hardswish": nn.Hardswish(),
                    "swish": nn.Swish(), None: None}[act]

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


# ---------------------------------------------------------- SqueezeNet ----
class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return paddle_ops.concat(
            [self.relu(self.expand1(s)), self.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """reference: vision/models/squeezenet.py (1.0 / 1.1)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            stem = [_ConvBNAct(3, 96, 7, 2, 3)]
            fires = [(96, 16, 64, 64), (128, 16, 64, 64),
                     (128, 32, 128, 128), ("pool",),
                     (256, 32, 128, 128), (256, 48, 192, 192),
                     (384, 48, 192, 192), (384, 64, 256, 256), ("pool",),
                     (512, 64, 256, 256)]
        else:
            stem = [_ConvBNAct(3, 64, 3, 2, 1)]
            fires = [(64, 16, 64, 64), (128, 16, 64, 64), ("pool",),
                     (128, 32, 128, 128), (256, 32, 128, 128), ("pool",),
                     (256, 48, 192, 192), (384, 48, 192, 192),
                     (384, 64, 256, 256), (512, 64, 256, 256)]
        layers = list(stem) + [nn.MaxPool2D(3, stride=2)]
        for f in fires:
            if f == ("pool",):
                layers.append(nn.MaxPool2D(3, stride=2))
            else:
                layers.append(_Fire(*f))
        self.features = nn.Sequential(*layers)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            return paddle_ops.flatten(x, start_axis=1)
        return x


def squeezenet1_0(pretrained=False, **kw):
    _no_pretrained(pretrained, "squeezenet1_0")
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    _no_pretrained(pretrained, "squeezenet1_1")
    return SqueezeNet("1.1", **kw)


# ------------------------------------------------------------ DenseNet ----
class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth, bn_size):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.conv1(self.relu(self.bn1(x)))
        y = self.conv2(self.relu(self.bn2(y)))
        return paddle_ops.concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_DENSE_CFG = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
              169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
              264: (6, 12, 64, 48)}


class DenseNet(nn.Layer):
    """reference: vision/models/densenet.py."""

    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers == 161:
            growth_rate, init_c = 48, 96
        else:
            init_c = 64
        self.num_classes = num_classes
        self.with_pool = with_pool
        blocks = _DENSE_CFG[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        c = init_c
        for bi, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth_rate, bn_size))
                c += growth_rate
            if bi != len(blocks) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(paddle_ops.flatten(x, start_axis=1))
        return x


def densenet121(pretrained=False, **kw):
    _no_pretrained(pretrained, "densenet121")
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    _no_pretrained(pretrained, "densenet161")
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    _no_pretrained(pretrained, "densenet169")
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    _no_pretrained(pretrained, "densenet201")
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    _no_pretrained(pretrained, "densenet264")
    return DenseNet(264, **kw)


# --------------------------------------------------------- ShuffleNetV2 ----
def _channel_shuffle(x, groups):
    from ...nn import functional as F
    return F.channel_shuffle(x, groups)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=2, padding=1, groups=in_c,
                          bias_attr=False),
                nn.BatchNorm2D(in_c),
                _ConvBNAct(in_c, branch_c, 1, 1, 0, act=act))
            b2_in = in_c
        else:
            self.branch1 = None
            b2_in = in_c // 2
        self.branch2 = nn.Sequential(
            _ConvBNAct(b2_in, branch_c, 1, 1, 0, act=act),
            nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                      groups=branch_c, bias_attr=False),
            nn.BatchNorm2D(branch_c),
            _ConvBNAct(branch_c, branch_c, 1, 1, 0, act=act))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = paddle_ops.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle_ops.concat([self.branch1(x), self.branch2(x)],
                                    axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.25: (24, (24, 48, 96), 512), 0.33: (24, (32, 64, 128), 512),
    0.5: (24, (48, 96, 192), 1024), 1.0: (24, (116, 232, 464), 1024),
    1.5: (24, (176, 352, 704), 1024), 2.0: (24, (244, 488, 976), 2048),
}


class ShuffleNetV2(nn.Layer):
    """reference: vision/models/shufflenetv2.py."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stem_c, stage_cs, final_c = _SHUFFLE_CFG[scale]
        self.conv1 = _ConvBNAct(3, stem_c, 3, 2, 1, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = stem_c
        for sc, repeat in zip(stage_cs, (4, 8, 4)):
            units = [_ShuffleUnit(in_c, sc, 2, act=act)]
            units += [_ShuffleUnit(sc, sc, 1, act=act)
                      for _ in range(repeat - 1)]
            stages.append(nn.Sequential(*units))
            in_c = sc
        self.stages = nn.LayerList(stages)
        self.conv_last = _ConvBNAct(in_c, final_c, 1, 1, 0, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(final_c, num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(paddle_ops.flatten(x, start_axis=1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_x0_25")
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_x0_5")
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_x1_0")
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_x1_5")
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_x2_0")
    return ShuffleNetV2(2.0, **kw)


# ---------------------------------------------------------- MobileNetV3 ----
class _SEModule(nn.Layer):
    def __init__(self, c, reduction=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, c // reduction, 1)
        self.fc2 = nn.Conv2D(c // reduction, c, 1)
        self.relu = nn.ReLU()
        self.hs = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hs(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_ConvBNAct(in_c, exp, 1, 1, 0, act=act))
        layers.append(_ConvBNAct(exp, exp, k, stride, (k - 1) // 2,
                                 groups=exp, act=act))
        if se:
            layers.append(_SEModule(exp))
        layers.append(_ConvBNAct(exp, out_c, 1, 1, 0, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        y = self.block(x)
        return x + y if self.use_res else y


_MBV3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_MBV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    """reference: vision/models/mobilenetv3.py (Large/Small)."""

    def __init__(self, config, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        from .mobilenet import _make_divisible as _md
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _md(16 * scale)
        self.conv1 = _ConvBNAct(3, in_c, 3, 2, 1, act="hardswish")
        blocks = []
        for k, exp, out_c, se, act, stride in config:
            blocks.append(_MBV3Block(in_c, _md(exp * scale),
                                     _md(out_c * scale), k, stride, se,
                                     act))
            in_c = _md(out_c * scale)
        self.blocks = nn.Sequential(*blocks)
        mid = _md(in_c * 6)
        self.conv2 = _ConvBNAct(in_c, mid, 1, 1, 0, act="hardswish")
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(mid, last_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.conv2(self.blocks(self.conv1(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(paddle_ops.flatten(x, start_axis=1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained, "mobilenet_v3_large")
    return MobileNetV3Large(scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    _no_pretrained(pretrained, "mobilenet_v3_small")
    return MobileNetV3Small(scale=scale, **kw)


# ------------------------------------------------- GoogLeNet/InceptionV3 ----
class _InceptionA(nn.Layer):
    """The classic 4-branch inception cell (1x1 / 3x3 / double-3x3 /
    pool-proj); parameterized widths cover both GoogLeNet and the
    InceptionV3 A-blocks."""

    def __init__(self, in_c, c1, c3r, c3, cd3r, cd3, cp):
        super().__init__()
        self.b1 = _ConvBNAct(in_c, c1, 1, 1, 0)
        self.b3 = nn.Sequential(_ConvBNAct(in_c, c3r, 1, 1, 0),
                                _ConvBNAct(c3r, c3, 3, 1, 1))
        self.bd3 = nn.Sequential(_ConvBNAct(in_c, cd3r, 1, 1, 0),
                                 _ConvBNAct(cd3r, cd3, 3, 1, 1),
                                 _ConvBNAct(cd3, cd3, 3, 1, 1))
        self.bp = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _ConvBNAct(in_c, cp, 1, 1, 0))

    def forward(self, x):
        return paddle_ops.concat(
            [self.b1(x), self.b3(x), self.bd3(x), self.bp(x)], axis=1)


class GoogLeNet(nn.Layer):
    """reference: vision/models/googlenet.py (inception v1; BN flavour,
    aux heads omitted — inference/training parity path)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBNAct(3, 64, 7, 2, 3), nn.MaxPool2D(3, stride=2,
                                                     padding=1),
            _ConvBNAct(64, 64, 1, 1, 0), _ConvBNAct(64, 192, 3, 1, 1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.i3a = _InceptionA(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _InceptionA(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i4a = _InceptionA(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _InceptionA(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _InceptionA(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _InceptionA(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _InceptionA(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.i5a = _InceptionA(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _InceptionA(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(paddle_ops.flatten(x, start_axis=1)))
        return x


def googlenet(pretrained=False, **kw):
    _no_pretrained(pretrained, "googlenet")
    return GoogLeNet(**kw)


class InceptionV3(nn.Layer):
    """reference: vision/models/inceptionv3.py — stem + A-cells; the full
    B/C factorized cells share the same concat-of-branches structure (the
    A-cell above), kept at the widths of the v3 A stage."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBNAct(3, 32, 3, 2, 0), _ConvBNAct(32, 32, 3, 1, 0),
            _ConvBNAct(32, 64, 3, 1, 1), nn.MaxPool2D(3, stride=2),
            _ConvBNAct(64, 80, 1, 1, 0), _ConvBNAct(80, 192, 3, 1, 0),
            nn.MaxPool2D(3, stride=2))
        self.a1 = _InceptionA(192, 64, 48, 64, 64, 96, 32)
        self.a2 = _InceptionA(256, 64, 48, 64, 64, 96, 64)
        self.a3 = _InceptionA(288, 64, 48, 64, 64, 96, 64)
        self.reduce = nn.Sequential(_ConvBNAct(288, 768, 3, 2, 0))
        self.a4 = _InceptionA(768, 192, 128, 192, 128, 192, 192)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(768, num_classes)

    def forward(self, x):
        x = self.a3(self.a2(self.a1(self.stem(x))))
        x = self.a4(self.reduce(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(paddle_ops.flatten(x, start_axis=1)))
        return x


def inception_v3(pretrained=False, **kw):
    _no_pretrained(pretrained, "inception_v3")
    return InceptionV3(**kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_x0_33")
    return ShuffleNetV2(0.33, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    _no_pretrained(pretrained, "shufflenet_v2_swish")
    return ShuffleNetV2(1.0, act="swish", **kw)
