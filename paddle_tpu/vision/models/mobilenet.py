"""MobileNet V1/V2 (reference: python/paddle/vision/models/
{mobilenetv1,mobilenetv2}.py)."""
from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNRelu(nn.Layer):
    def __init__(self, in_c, out_c, kernel=3, stride=1, padding=1,
                 groups=1, relu6=False):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6() if relu6 else nn.ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = ConvBNRelu(in_c, in_c, 3, stride, 1, groups=in_c)
        self.pw = ConvBNRelu(in_c, out_c, 1, 1, 0)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return int(ch * scale)
        cfg = [(c(32), c(64), 1), (c(64), c(128), 2), (c(128), c(128), 1),
               (c(128), c(256), 2), (c(256), c(256), 1),
               (c(256), c(512), 2)] + [(c(512), c(512), 1)] * 5 + \
              [(c(512), c(1024), 2), (c(1024), c(1024), 1)]
        layers = [ConvBNRelu(3, c(32), 3, 2, 1)]
        for in_c, out_c, s in cfg:
            layers.append(DepthwiseSeparable(in_c, out_c, s))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNRelu(inp, hidden, 1, 1, 0, relu6=True))
        layers += [
            ConvBNRelu(hidden, hidden, 3, stride, 1, groups=hidden,
                       relu6=True),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        layers = [ConvBNRelu(3, in_c, 3, 2, 1, relu6=True)]
        for t, ch, n, s in cfg:
            out_c = _make_divisible(ch * scale)
            for i in range(n):
                layers.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(ConvBNRelu(in_c, last, 1, 1, 0, relu6=True))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(pretrained=False, scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)
