"""Vision transforms (reference: python/paddle/vision/transforms/transforms.py).
numpy-based host-side preprocessing (CHW float arrays)."""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


def _chw(img):
    img = np.asarray(img)
    if img.ndim == 2:
        return img[None]
    if img.shape[-1] in (1, 3, 4) and img.shape[0] not in (1, 3, 4):
        return np.transpose(img, (2, 0, 1))
    return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _chw(img).astype(np.float32)
        if img.max() > 1.5:
            img = img / 255.0
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        c = img.shape[0]
        return (img - self.mean[:c, None, None]) / self.std[:c, None, None]


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        img = _chw(np.asarray(img))
        c = img.shape[0]
        out = jax.image.resize(np.asarray(img, np.float32),
                               (c,) + self.size, method="linear")
        return np.asarray(out)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        h, w = img.shape[-2:]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[..., i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            img = np.pad(img, ((0, 0), (p[1], p[3]), (p[0], p[2])))
        h, w = img.shape[-2:]
        th, tw = self.size
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return img[..., i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[..., ::-1, :].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * alpha, 0,
                       255 if np.asarray(img).max() > 1.5 else 1.0)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        h, w = img.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            ar = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = img[..., i:i + th, j:j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(img)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[..., ::-1].copy()


def vflip(img):
    return np.asarray(img)[..., ::-1, :].copy()
