"""Vision transforms (reference: python/paddle/vision/transforms/transforms.py).
numpy-based host-side preprocessing (CHW float arrays)."""
from __future__ import annotations

import numbers
import random
from typing import List, Optional, Sequence

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


def _chw(img):
    img = np.asarray(img)
    if img.ndim == 2:
        return img[None]
    if img.shape[-1] in (1, 3, 4) and img.shape[0] not in (1, 3, 4):
        return np.transpose(img, (2, 0, 1))
    return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _chw(img).astype(np.float32)
        if img.max() > 1.5:
            img = img / 255.0
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        c = img.shape[0]
        return (img - self.mean[:c, None, None]) / self.std[:c, None, None]


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax
        img = _chw(np.asarray(img))
        c = img.shape[0]
        out = jax.image.resize(np.asarray(img, np.float32),
                               (c,) + self.size, method="linear")
        return np.asarray(out)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        h, w = img.shape[-2:]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[..., i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            img = np.pad(img, ((0, 0), (p[1], p[3]), (p[0], p[2])))
        h, w = img.shape[-2:]
        th, tw = self.size
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return img[..., i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[..., ::-1, :].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * alpha, 0,
                       255 if np.asarray(img).max() > 1.5 else 1.0)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        img = _chw(np.asarray(img))
        h, w = img.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            ar = random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                crop = img[..., i:i + th, j:j + tw]
                return Resize(self.size)._apply_image(crop)
        return Resize(self.size)._apply_image(img)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[..., ::-1].copy()


def vflip(img):
    return np.asarray(img)[..., ::-1, :].copy()


# ---------------- functional long tail ----------------
# reference: python/paddle/vision/transforms/functional.py (+ the cv2/PIL
# backends functional_cv2.py / functional_pil.py) — numpy backend here.

def crop(img, top, left, height, width):
    """reference: transforms/functional.py crop."""
    img = np.asarray(img)
    chw = img.ndim == 2 or (img.shape[0] in (1, 3, 4)
                            and img.shape[-1] not in (1, 3, 4))
    if img.ndim == 2:
        return img[top:top + height, left:left + width].copy()
    if chw:
        return img[..., top:top + height, left:left + width].copy()
    return img[top:top + height, left:left + width, :].copy()


def center_crop(img, output_size):
    """reference: functional.py center_crop."""
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _chw(np.asarray(img))
    h, w = arr.shape[-2:]
    th, tw = output_size
    i = max(0, (h - th) // 2)
    j = max(0, (w - tw) // 2)
    return arr[..., i:i + th, j:j + tw].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    """reference: functional.py pad — padding int | (lr, tb) | (l, t, r, b)."""
    arr = _chw(np.asarray(img))
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)
    elif len(padding) == 2:
        l = r = int(padding[0])
        t = b = int(padding[1])
    else:
        l, t, r, b = [int(p) for p in padding]
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    cfg = [(0, 0)] * (arr.ndim - 2) + [(t, b), (l, r)]
    if mode == "constant":
        return np.pad(arr, cfg, mode, constant_values=fill)
    return np.pad(arr, cfg, mode)


def to_grayscale(img, num_output_channels=1):
    """reference: functional.py to_grayscale (ITU-R 601-2 luma)."""
    arr = _chw(np.asarray(img)).astype(np.float32)
    if arr.shape[0] == 1:
        g = arr
    else:
        g = (0.299 * arr[0] + 0.587 * arr[1] + 0.114 * arr[2])[None]
    out = np.repeat(g, num_output_channels, axis=0)
    return out.astype(np.asarray(img).dtype) \
        if np.issubdtype(np.asarray(img).dtype, np.integer) else out


def adjust_brightness(img, brightness_factor):
    """reference: functional.py adjust_brightness — img * factor
    (preserves the input dtype, incl. uint8)."""
    src_dtype = np.asarray(img).dtype
    arr = np.asarray(img).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    return np.clip(arr * brightness_factor, 0, hi).astype(src_dtype)


def adjust_contrast(img, contrast_factor):
    """reference: functional.py adjust_contrast — blend with the mean of
    the grayscale image."""
    src_dtype = np.asarray(img).dtype
    arr = _chw(np.asarray(img)).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    mean = to_grayscale(arr).mean()
    return np.clip((1 - contrast_factor) * mean
                   + contrast_factor * arr, 0, hi).astype(src_dtype)


def _rgb_to_hsv(rgb):
    r, g, b = rgb[0], rgb[1], rgb[2]
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    rc = (maxc - r) / np.maximum(d, 1e-12)
    gc = (maxc - g) / np.maximum(d, 1e-12)
    bc = (maxc - b) / np.maximum(d, 1e-12)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(d == 0, 0.0, h)
    return np.stack([(h / 6.0) % 1.0, s, v])


def _hsv_to_rgb(hsv):
    h, s, v = hsv[0], hsv[1], hsv[2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return np.stack([r, g, b])


def adjust_hue(img, hue_factor):
    """reference: functional.py adjust_hue — shift hue by hue_factor
    (|f| <= 0.5) through HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    src_dtype = np.asarray(img).dtype
    arr = _chw(np.asarray(img)).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    hsv = _rgb_to_hsv(arr / hi)
    hsv[0] = (hsv[0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv) * hi
    return np.clip(out, 0, hi).astype(src_dtype)


def adjust_saturation(img, saturation_factor):
    """reference: functional.py adjust_saturation — blend with gray."""
    src_dtype = np.asarray(img).dtype
    arr = _chw(np.asarray(img)).astype(np.float32)
    hi = 255.0 if arr.max() > 1.5 else 1.0
    g = to_grayscale(arr)
    g3 = np.repeat(g, arr.shape[0], axis=0)
    return np.clip((1 - saturation_factor) * g3
                   + saturation_factor * arr, 0, hi).astype(src_dtype)


def _inverse_sample(img, inv, out_hw, interpolation="bilinear", fill=0.0):
    """Sample img (C,H,W) at positions given by the inverse map
    ``inv(ys, xs) -> (src_y, src_x)`` — the shared engine for rotate/
    affine/perspective (reference backends use cv2.warpAffine etc.)."""
    c, h, w = img.shape
    oh, ow = out_hw
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float32),
                         np.arange(ow, dtype=np.float32), indexing="ij")
    sy, sx = inv(ys, xs)
    if interpolation == "nearest":
        iy = np.round(sy).astype(np.int64)
        ix = np.round(sx).astype(np.int64)
        valid = (iy >= 0) & (iy < h) & (ix >= 0) & (ix < w)
        iy = np.clip(iy, 0, h - 1)
        ix = np.clip(ix, 0, w - 1)
        out = img[:, iy, ix]
        return np.where(valid[None], out, fill).astype(np.float32)
    y0 = np.floor(sy).astype(np.int64)
    x0 = np.floor(sx).astype(np.int64)
    wy = sy - y0
    wx = sx - x0
    out = np.zeros((c, oh, ow), np.float32)
    for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)),
                        (0, 1, (1 - wy) * wx),
                        (1, 0, wy * (1 - wx)),
                        (1, 1, wy * wx)):
        yy = y0 + dy
        xx = x0 + dx
        valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        yc = np.clip(yy, 0, h - 1)
        xc = np.clip(xx, 0, w - 1)
        out += wgt[None] * np.where(valid[None], img[:, yc, xc], fill)
    return out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """reference: functional.py rotate (degrees, counter-clockwise)."""
    arr = _chw(np.asarray(img)).astype(np.float32)
    h, w = arr.shape[-2:]
    a = -np.deg2rad(angle)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    if center is not None:
        cx, cy = center
    if expand:
        corners = np.array([[0, 0], [0, w - 1], [h - 1, 0],
                            [h - 1, w - 1]], np.float32)
        ang = np.deg2rad(angle)
        rot = np.array([[np.cos(ang), -np.sin(ang)],
                        [np.sin(ang), np.cos(ang)]])
        rel = corners - [cy, cx]
        new = rel @ rot.T
        oh = int(np.ceil(new[:, 0].max() - new[:, 0].min()) + 1)
        ow = int(np.ceil(new[:, 1].max() - new[:, 1].min()) + 1)
        ncy, ncx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow = h, w
        ncy, ncx = cy, cx

    def inv(ys, xs):
        dy = ys - ncy
        dx = xs - ncx
        sy = np.cos(a) * dy - np.sin(a) * dx + cy
        sx = np.sin(a) * dy + np.cos(a) * dx + cx
        return sy, sx
    return _inverse_sample(arr, inv, (oh, ow), interpolation, fill)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """reference: functional.py affine — rotation + translation + scale +
    shear about the center, matching torchvision's parameterization."""
    arr = _chw(np.asarray(img)).astype(np.float32)
    h, w = arr.shape[-2:]
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    if center is not None:
        cx, cy = center
    rot = np.deg2rad(angle)
    sx_sh, sy_sh = [np.deg2rad(s) for s in (
        shear if isinstance(shear, (list, tuple)) else (shear, 0.0))]
    # forward matrix in (x, y): R(rot) * Shear * scale
    a = np.cos(rot - sy_sh) / max(np.cos(sy_sh), 1e-12)
    b = -np.cos(rot - sy_sh) * np.tan(sx_sh) / max(
        np.cos(sy_sh), 1e-12) - np.sin(rot)
    c = np.sin(rot - sy_sh) / max(np.cos(sy_sh), 1e-12)
    d = -np.sin(rot - sy_sh) * np.tan(sx_sh) / max(
        np.cos(sy_sh), 1e-12) + np.cos(rot)
    m = scale * np.array([[a, b], [c, d]], np.float32)
    minv = np.linalg.inv(m)
    tx, ty = translate

    def inv(ys, xs):
        dx = xs - cx - tx
        dy = ys - cy - ty
        sxp = minv[0, 0] * dx + minv[0, 1] * dy + cx
        syp = minv[1, 0] * dx + minv[1, 1] * dy + cy
        return syp, sxp
    return _inverse_sample(arr, inv, (h, w), interpolation, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """reference: functional.py perspective — projective warp mapping
    startpoints -> endpoints ((x, y) corner lists)."""
    arr = _chw(np.asarray(img)).astype(np.float32)
    h, w = arr.shape[-2:]
    # solve the 8-dof homography taking END -> START (inverse map)
    A = []
    bvec = []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        bvec.append(sx)
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec.append(sy)
    coef = np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(bvec, np.float64))
    hmat = np.append(coef, 1.0).reshape(3, 3)

    def inv(ys, xs):
        den = hmat[2, 0] * xs + hmat[2, 1] * ys + hmat[2, 2]
        sx = (hmat[0, 0] * xs + hmat[0, 1] * ys + hmat[0, 2]) / den
        sy = (hmat[1, 0] * xs + hmat[1, 1] * ys + hmat[1, 2]) / den
        return sy, sx
    return _inverse_sample(arr, inv, (h, w), interpolation, fill)


def erase(img, i, j, h, w, v, inplace=False):
    """reference: functional.py erase — fill the region with v."""
    from .._core.tensor import Tensor
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        val = img._value
        region = jnp.broadcast_to(jnp.asarray(v, val.dtype),
                                  val[..., i:i + h, j:j + w].shape)
        out = val.at[..., i:i + h, j:j + w].set(region)
        if inplace:
            img._inplace_assign(out)
            return img
        return Tensor(out, _internal=True)
    arr = np.asarray(img)
    out = arr if inplace else arr.copy()
    out[..., i:i + h, j:j + w] = v
    return out


# ---------------- transform classes ----------------
class ContrastTransform(BaseTransform):
    """reference: transforms.py ContrastTransform."""

    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = 1 + np.random.uniform(-self.value, self.value)
        return adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    """reference: transforms.py SaturationTransform."""

    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("saturation value must be non-negative")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = 1 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, factor)


class HueTransform(BaseTransform):
    """reference: transforms.py HueTransform."""

    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    """reference: transforms.py ColorJitter — random order of the four
    jitters."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    """reference: transforms.py Grayscale."""

    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    """reference: transforms.py Pad."""

    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.mode)


class RandomRotation(BaseTransform):
    """reference: transforms.py RandomRotation."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.a = (interpolation, expand, center, fill)

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        it, ex, ce, fi = self.a
        return rotate(img, angle, it, ex, ce, fi)


class RandomAffine(BaseTransform):
    """reference: transforms.py RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.a = (interpolation, fill, center)

    def _apply_image(self, img):
        arr = _chw(np.asarray(img))
        h, w = arr.shape[-2:]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0],
                                   self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1],
                                   self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        sh = 0.0
        if self.shear is not None:
            shr = self.shear if isinstance(
                self.shear, (list, tuple)) else (-self.shear, self.shear)
            sh = np.random.uniform(shr[0], shr[1])
        it, fi, ce = self.a
        return affine(img, angle, (tx, ty), sc, sh, it, fi, ce)


class RandomPerspective(BaseTransform):
    """reference: transforms.py RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.a = (interpolation, fill)

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _chw(np.asarray(img))
        h, w = arr.shape[-2:]
        d = self.distortion_scale
        half_h = int(h * d / 2)
        half_w = int(w * d / 2)
        tl = (np.random.randint(0, half_w + 1),
              np.random.randint(0, half_h + 1))
        tr = (w - 1 - np.random.randint(0, half_w + 1),
              np.random.randint(0, half_h + 1))
        br = (w - 1 - np.random.randint(0, half_w + 1),
              h - 1 - np.random.randint(0, half_h + 1))
        bl = (np.random.randint(0, half_w + 1),
              h - 1 - np.random.randint(0, half_h + 1))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        it, fi = self.a
        return perspective(img, start, [tl, tr, br, bl], it, fi)


class RandomErasing(BaseTransform):
    """reference: transforms.py RandomErasing."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = _chw(np.asarray(img))
        h, w = arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                v = self.value if not isinstance(self.value, str) else \
                    np.random.randn(arr.shape[0], eh, ew)
                return erase(arr, i, j, eh, ew, v, self.inplace)
        return arr
