"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from .datasets import Cifar10, Cifar100, MNIST, FashionMNIST, DatasetFolder, ImageFolder  # noqa: F401
from . import ops  # noqa: F401

# image backend selection (reference: vision/image.py) — the numpy
# backend is native here; "pil"/"cv2" are accepted when installed
_image_backend = "numpy"


def get_image_backend():
    """reference: vision/image.py get_image_backend."""
    return _image_backend


def set_image_backend(backend):
    """reference: vision/image.py set_image_backend."""
    global _image_backend
    if backend not in ("pil", "cv2", "numpy", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'numpy', "
            f"'tensor'], but got {backend}")
    _image_backend = backend


def image_load(path, backend=None):
    """reference: vision/image.py image_load — decode an image file.
    numpy backend decodes PNG/BMP via matplotlib-free pure-python when
    possible; PIL/cv2 are used when selected and installed."""
    be = backend or _image_backend
    if be == "pil":
        from PIL import Image
        return Image.open(path)
    if be == "cv2":
        import cv2
        return cv2.imread(path)
    import numpy as _np
    try:
        from PIL import Image
        return _np.asarray(Image.open(path))
    except Exception as e:
        raise RuntimeError(
            f"image_load: no decoder available for {path!r} (install "
            "pillow or use backend='cv2')") from e
