"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from .datasets import Cifar10, Cifar100, MNIST, FashionMNIST, DatasetFolder, ImageFolder  # noqa: F401
from . import ops  # noqa: F401
