"""Vision ops (reference: python/paddle/vision/ops.py — nms:1637,
box_iou-style utilities; kernel paddle/phi/kernels/nms_kernel.h).

TPU-native note: NMS is sequential by nature (each suppression depends on
prior keeps). This implementation runs the O(n^2) IoU matrix on device
(one batched jnp computation, MXU-friendly) and the greedy scan via
lax.while-free numpy on host — NMS sits at the end of detection pipelines
where the candidate count is small.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from ..ops._registry import as_tensor, raw

__all__ = ["nms", "box_iou"]


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of (N, 4) and (M, 4) xyxy boxes."""
    a = raw(as_tensor(boxes1)).astype(jnp.float32)
    b = raw(as_tensor(boxes2)).astype(jnp.float32)
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return Tensor(inter / jnp.maximum(union, 1e-9), _internal=True)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy non-maximum suppression (reference: vision/ops.py:1637).
    boxes: (N, 4) xyxy. Returns kept indices sorted by descending score."""
    bv = raw(as_tensor(boxes))
    n = bv.shape[0]
    if n == 0:
        return Tensor(jnp.zeros((0,), jnp.int32), _internal=True)
    sv = raw(as_tensor(scores)) if scores is not None else None

    iou = np.asarray(jax.device_get(raw(box_iou(boxes, boxes))))
    order = np.argsort(-np.asarray(jax.device_get(sv))) \
        if sv is not None else np.arange(n)
    cats = np.asarray(jax.device_get(raw(as_tensor(category_idxs)))) \
        if category_idxs is not None else None

    suppressed = np.zeros(n, bool)
    keep = []
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        over = iou[i] > iou_threshold
        if cats is not None:
            over = over & (cats == cats[i])  # class-aware suppression
        over[i] = False
        suppressed |= over
    keep = np.asarray(keep, np.int32)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)
