"""Vision ops (reference: python/paddle/vision/ops.py — nms:1637,
box_iou-style utilities; kernel paddle/phi/kernels/nms_kernel.h).

TPU-native note: NMS is sequential by nature (each suppression depends on
prior keeps). This implementation runs the O(n^2) IoU matrix on device
(one batched jnp computation, MXU-friendly) and the greedy scan via
lax.while-free numpy on host — NMS sits at the end of detection pipelines
where the candidate count is small.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core.autograd import apply
from ..ops._registry import as_tensor, raw
from ..nn.layer.layers import Layer

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "psroi_pool",
           "box_coder", "prior_box", "yolo_box", "yolo_loss",
           "matrix_nms", "deform_conv2d", "distribute_fpn_proposals",
           "generate_proposals", "read_file", "decode_jpeg",
           "yolo_box_head", "yolo_box_post", "collect_fpn_proposals",
           "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D"]


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU of (N, 4) and (M, 4) xyxy boxes."""
    a = raw(as_tensor(boxes1)).astype(jnp.float32)
    b = raw(as_tensor(boxes2)).astype(jnp.float32)
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return Tensor(inter / jnp.maximum(union, 1e-9), _internal=True)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy non-maximum suppression (reference: vision/ops.py:1637).
    boxes: (N, 4) xyxy. Returns kept indices sorted by descending score."""
    bv = raw(as_tensor(boxes))
    n = bv.shape[0]
    if n == 0:
        return Tensor(jnp.zeros((0,), jnp.int32), _internal=True)
    sv = raw(as_tensor(scores)) if scores is not None else None

    iou = np.asarray(jax.device_get(raw(box_iou(boxes, boxes))))
    order = np.argsort(-np.asarray(jax.device_get(sv))) \
        if sv is not None else np.arange(n)
    cats = np.asarray(jax.device_get(raw(as_tensor(category_idxs)))) \
        if category_idxs is not None else None

    suppressed = np.zeros(n, bool)
    keep = []
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        over = iou[i] > iou_threshold
        if cats is not None:
            over = over & (cats == cats[i])  # class-aware suppression
        over[i] = False
        suppressed |= over
    keep = np.asarray(keep, np.int32)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep), _internal=True)


# ---------------- detection operator long tail ----------------
# reference: python/paddle/vision/ops.py — roi_align/roi_pool/psroi_pool
# (kernels phi roi_align_kernel etc.), box_coder, prior_box, yolo_box,
# yolo_loss, matrix_nms, deform_conv2d, distribute_fpn_proposals,
# generate_proposals. jnp implementations: gather/scatter formulations
# XLA tiles; the host-dynamic ones (proposal generation, matrix_nms
# outputs) run on host like the reference's CPU kernels.

def _rois_with_batch(boxes, boxes_num):
    """(sum_n, 4) boxes + per-image counts -> (sum_n,) batch ids."""
    bn = raw(as_tensor(boxes_num)).astype(np.int64)
    return np.repeat(np.arange(len(bn)), bn)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """reference: vision/ops.py roi_align (phi roi_align_kernel) —
    bilinear sampling over each RoI bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bids = jnp.asarray(_rois_with_batch(boxes, boxes_num))

    def f(feat, bx):
        C = feat.shape[1]
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / oh
        bin_w = rw / ow
        # adaptive default: the reference samples ceil(roi/bin) points
        # per bin PER ROI (dynamic); the static-shape equivalent uses the
        # feature-map upper bound ceil(feat/out) for every RoI — exact for
        # full-image RoIs, oversampled (never undersampled vs a fixed 2)
        # for small ones
        H_in, W_in = feat.shape[-2:]
        sr_h = sampling_ratio if sampling_ratio > 0 else max(
            1, -(-int(H_in) // oh))
        sr_w = sampling_ratio if sampling_ratio > 0 else max(
            1, -(-int(W_in) // ow))
        # sample grid: (R, oh, ow, sr_h, sr_w)
        iy = (jnp.arange(sr_h) + 0.5) / sr_h
        ix = (jnp.arange(sr_w) + 0.5) / sr_w
        gy = (y1[:, None, None] + (jnp.arange(oh)[None, :, None]
              + iy[None, None, :]) * bin_h[:, None, None])
        gx = (x1[:, None, None] + (jnp.arange(ow)[None, :, None]
              + ix[None, None, :]) * bin_w[:, None, None])

        def sample(img, ys, xs):
            H, W = img.shape[-2:]
            y0 = jnp.floor(ys)
            x0 = jnp.floor(xs)
            wy = ys - y0
            wx = xs - x0
            out = 0.0
            for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)),
                                (0, 1, (1 - wy) * wx),
                                (1, 0, wy * (1 - wx)),
                                (1, 1, wy * wx)):
                yy = jnp.clip(y0 + dy, 0, H - 1).astype(jnp.int32)
                xx = jnp.clip(x0 + dx, 0, W - 1).astype(jnp.int32)
                valid = ((ys >= -1) & (ys <= H) & (xs >= -1) & (xs <= W))
                out = out + wgt * jnp.where(valid, img[..., yy, xx], 0.0)
            return out

        def per_roi(b, gyr, gxr):
            img = feat[b]  # (C, H, W)
            ys = jnp.broadcast_to(gyr[:, None, :, None],
                                  (oh, ow, sr_h, sr_w))
            xs = jnp.broadcast_to(gxr[None, :, None, :],
                                  (oh, ow, sr_h, sr_w))
            # sample per channel: vectorize channel via vmap
            samp = jax.vmap(lambda ch: sample(ch, ys, xs))(img)
            return jnp.mean(samp, axis=(-2, -1))      # (C, oh, ow)

        return jax.vmap(per_roi)(bids, gy, gx)
    return apply(f, as_tensor(x), as_tensor(boxes), name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """reference: vision/ops.py roi_pool (max pooling per RoI bin)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bids = jnp.asarray(_rois_with_batch(boxes, boxes_num))

    def f(feat, bx):
        H, W = feat.shape[-2:]
        x1 = jnp.round(bx[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(bx[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(bx[:, 2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(bx[:, 3] * spatial_scale).astype(jnp.int32)

        def per_roi(b, xx1, yy1, xx2, yy2):
            img = feat[b]
            rh = jnp.maximum(yy2 - yy1 + 1, 1)
            rw = jnp.maximum(xx2 - xx1 + 1, 1)
            ys = jnp.arange(H)
            xs = jnp.arange(W)
            out = jnp.full((feat.shape[1], oh, ow), -jnp.inf)
            # bin index of each pixel (pixels outside the roi -> -1)
            by = jnp.where((ys >= yy1) & (ys <= yy2),
                           jnp.clip(((ys - yy1) * oh) // rh, 0, oh - 1),
                           -1)
            bxm = jnp.where((xs >= xx1) & (xs <= xx2),
                            jnp.clip(((xs - xx1) * ow) // rw, 0, ow - 1),
                            -1)
            oneh_y = (by[:, None] == jnp.arange(oh)[None, :])  # (H, oh)
            oneh_x = (bxm[:, None] == jnp.arange(ow)[None, :])  # (W, ow)
            masked = jnp.where(
                oneh_y[None, :, None, :, None]
                & oneh_x[None, None, :, None, :],
                img[:, :, :, None, None], -jnp.inf)
            pooled = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(pooled), pooled, 0.0)
        return jax.vmap(per_roi)(bids, x1, y1, x2, y2)
    return apply(f, as_tensor(x), as_tensor(boxes), name="roi_pool")


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """reference: vision/ops.py psroi_pool — position-sensitive RoI
    average pooling: input C = out_C * oh * ow; bin (i, j) reads its own
    channel group."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bids = jnp.asarray(_rois_with_batch(boxes, boxes_num))

    def f(feat, bx):
        C = feat.shape[1]
        out_c = C // (oh * ow)
        H, W = feat.shape[-2:]
        x1 = bx[:, 0] * spatial_scale
        y1 = bx[:, 1] * spatial_scale
        x2 = bx[:, 2] * spatial_scale
        y2 = bx[:, 3] * spatial_scale
        bin_h = (y2 - y1) / oh
        bin_w = (x2 - x1) / ow

        def per_roi(b, xx1, yy1, bh, bw):
            img = feat[b].reshape(out_c, oh, ow, H, W)
            ys = jnp.arange(H, dtype=jnp.float32)
            xs = jnp.arange(W, dtype=jnp.float32)
            outs = []
            for i in range(oh):
                for j in range(ow):
                    ylo = yy1 + i * bh
                    yhi = yy1 + (i + 1) * bh
                    xlo = xx1 + j * bw
                    xhi = xx1 + (j + 1) * bw
                    my = (ys >= jnp.floor(ylo)) & (ys < jnp.ceil(yhi))
                    mx = (xs >= jnp.floor(xlo)) & (xs < jnp.ceil(xhi))
                    m = my[:, None] & mx[None, :]
                    cnt = jnp.maximum(jnp.sum(m), 1)
                    outs.append(jnp.sum(
                        jnp.where(m[None], img[:, i, j], 0.0),
                        axis=(-2, -1)) / cnt)
            return jnp.stack(outs, axis=-1).reshape(out_c, oh, ow)
        return jax.vmap(per_roi)(bids, x1, y1, bin_h, bin_w)
    return apply(f, as_tensor(x), as_tensor(boxes), name="psroi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """reference: vision/ops.py box_coder (phi box_coder_kernel)."""
    pb = as_tensor(prior_box)
    tb = as_tensor(target_box)
    pbv = None if prior_box_var is None or isinstance(
        prior_box_var, (list, tuple)) else as_tensor(prior_box_var)
    var_list = prior_box_var if isinstance(prior_box_var, (list, tuple)) \
        else None
    args = [pb, tb] + ([pbv] if pbv is not None else [])

    def f(p, t, *rest):
        norm = 0.0 if box_normalized else 1.0
        pw = p[:, 2] - p[:, 0] + norm
        ph = p[:, 3] - p[:, 1] + norm
        pcx = p[:, 0] + pw / 2
        pcy = p[:, 1] + ph / 2
        if rest:
            v = rest[0]
        elif var_list is not None:
            v = jnp.asarray(var_list, jnp.float32)[None, :]
        else:
            v = jnp.ones((1, 4), jnp.float32)
        if code_type == "encode_center_size":
            tw = t[:, 2] - t[:, 0] + norm
            th = t[:, 3] - t[:, 1] + norm
            tcx = t[:, 0] + tw / 2
            tcy = t[:, 1] + th / 2
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :])], axis=-1)
            vv = v if v.ndim == 2 else v
            return out / (vv[None] if vv.ndim == 2 else vv)
        # decode_center_size: t (N, M, 4) deltas on priors along `axis`
        pw_ = pw[None, :, None] if axis == 0 else pw[:, None, None]
        ph_ = ph[None, :, None] if axis == 0 else ph[:, None, None]
        pcx_ = pcx[None, :, None] if axis == 0 else pcx[:, None, None]
        pcy_ = pcy[None, :, None] if axis == 0 else pcy[:, None, None]
        vv = v[None] if v.ndim == 2 else v
        d = t * vv if vv.shape[-1] == 4 else t
        dcx = d[..., 0:1] * pw_ + pcx_
        dcy = d[..., 1:2] * ph_ + pcy_
        dw = jnp.exp(d[..., 2:3]) * pw_
        dh = jnp.exp(d[..., 3:4]) * ph_
        return jnp.concatenate([dcx - dw / 2, dcy - dh / 2,
                                dcx + dw / 2 - norm,
                                dcy + dh / 2 - norm], axis=-1)
    return apply(f, *args, name="box_coder")


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
              flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """reference: vision/ops.py prior_box (SSD anchor generator)."""
    x = as_tensor(input)
    img = as_tensor(image)
    H, W = int(x.shape[-2]), int(x.shape[-1])
    IH, IW = int(img.shape[-2]), int(img.shape[-1])
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    sw = steps[0] or IW / W
    sh = steps[1] or IH / H
    boxes = []
    for i in range(H):
        for j in range(W):
            cx = (j + offset) * sw
            cy = (i + offset) * sh
            cell = []
            for k, ms in enumerate(min_sizes):
                if min_max_aspect_ratios_order:
                    cell.append((cx, cy, ms, ms))
                    if max_sizes:
                        sz = float(np.sqrt(ms * max_sizes[k]))
                        cell.append((cx, cy, sz, sz))
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        cell.append((cx, cy, ms * np.sqrt(ar),
                                     ms / np.sqrt(ar)))
                else:
                    for ar in ars:
                        cell.append((cx, cy, ms * np.sqrt(ar),
                                     ms / np.sqrt(ar)))
                    if max_sizes:
                        sz = float(np.sqrt(ms * max_sizes[k]))
                        cell.append((cx, cy, sz, sz))
            boxes.append(cell)
    nprior = len(boxes[0])
    arr = np.asarray(boxes, np.float32).reshape(H, W, nprior, 4)
    out = np.stack([
        (arr[..., 0] - arr[..., 2] / 2) / IW,
        (arr[..., 1] - arr[..., 3] / 2) / IH,
        (arr[..., 0] + arr[..., 2] / 2) / IW,
        (arr[..., 1] + arr[..., 3] / 2) / IH], axis=-1)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return (Tensor(jnp.asarray(out), _internal=True),
            Tensor(jnp.asarray(var), _internal=True))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """reference: vision/ops.py yolo_box (phi yolo_box_kernel) — decode
    YOLOv3 head predictions into boxes + scores."""
    anchors = list(anchors)
    na = len(anchors) // 2

    def f(pred, imsz):
        B, C, H, W = pred.shape
        p = pred.reshape(B, na, -1, H, W)
        bx = (jax.nn.sigmoid(p[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2)
        by = (jax.nn.sigmoid(p[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        cx = (bx + gx) / W
        cy = (by + gy) / H
        bw = jnp.exp(p[:, :, 2]) * aw / in_w
        bh = jnp.exp(p[:, :, 3]) * ah / in_h
        obj = jax.nn.sigmoid(p[:, :, 4])
        cls = jax.nn.sigmoid(p[:, :, 5:5 + class_num])
        score = obj[:, :, None] * cls
        imh = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * imw
        y1 = (cy - bh / 2) * imh
        x2 = (cx + bw / 2) * imw
        y2 = (cy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(B, -1, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(B, -1, class_num)
        keep = (obj.reshape(B, -1) > conf_thresh)[..., None]
        return boxes * keep, scores * keep
    return apply(f, as_tensor(x), as_tensor(img_size), name="yolo_box",
                 multi_out=True)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """reference: vision/ops.py yolo_loss (phi yolo_loss_kernel) —
    YOLOv3 training loss: coordinate + objectness + class terms with
    best-anchor assignment and ignore-region masking."""
    anchors = list(anchors)
    anchor_mask = list(anchor_mask)
    na = len(anchor_mask)

    def f(pred, gtb, gtl, *rest):
        B, C, H, W = pred.shape
        p = pred.reshape(B, na, -1, H, W)
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        px = jax.nn.sigmoid(p[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        py = jax.nn.sigmoid(p[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        pw = p[:, :, 2]
        ph = p[:, :, 3]
        obj_logit = p[:, :, 4]
        cls_logit = p[:, :, 5:5 + class_num]
        aw_all = jnp.asarray(anchors[0::2], jnp.float32)
        ah_all = jnp.asarray(anchors[1::2], jnp.float32)
        aw = aw_all[jnp.asarray(anchor_mask)]
        ah = ah_all[jnp.asarray(anchor_mask)]

        # gt: (B, G, 4) cx cy w h normalized to [0, 1]
        G = gtb.shape[1]
        gx = gtb[..., 0]
        gy = gtb[..., 1]
        gw = gtb[..., 2]
        gh = gtb[..., 3]
        valid = gw > 0

        # best anchor per gt over ALL anchors (shape-only IoU)
        inter = (jnp.minimum(gw[..., None] * in_w, aw_all)
                 * jnp.minimum(gh[..., None] * in_h, ah_all))
        union = (gw[..., None] * in_w * gh[..., None] * in_h
                 + aw_all * ah_all - inter)
        best = jnp.argmax(inter / jnp.maximum(union, 1e-12), axis=-1)

        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)

        loss = jnp.zeros((B,), jnp.float32)
        obj_target = jnp.zeros((B, na, H, W))
        # ignore mask (reference yolov3_loss kernel): predicted boxes
        # whose best IoU with ANY gt exceeds ignore_thresh are excluded
        # from the negative objectness term
        gxs = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gys = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        aw_m = aw[None, :, None, None]
        ah_m = ah[None, :, None, None]
        pcx = (jax.nn.sigmoid(p[:, :, 0]) + gxs) / W
        pcy = (jax.nn.sigmoid(p[:, :, 1]) + gys) / H
        pww = jnp.exp(jnp.clip(pw, -10, 10)) * aw_m / in_w
        phh = jnp.exp(jnp.clip(ph, -10, 10)) * ah_m / in_h
        px1 = pcx - pww / 2
        py1 = pcy - phh / 2
        px2 = pcx + pww / 2
        py2 = pcy + phh / 2
        g_x1 = (gx - gw / 2)[:, None, None, None, :]
        g_y1 = (gy - gh / 2)[:, None, None, None, :]
        g_x2 = (gx + gw / 2)[:, None, None, None, :]
        g_y2 = (gy + gh / 2)[:, None, None, None, :]
        ix1 = jnp.maximum(px1[..., None], g_x1)
        iy1 = jnp.maximum(py1[..., None], g_y1)
        ix2 = jnp.minimum(px2[..., None], g_x2)
        iy2 = jnp.minimum(py2[..., None], g_y2)
        inter_p = (jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0))
        area_p = (pww * phh)[..., None]
        area_g = (gw * gh)[:, None, None, None, :]
        iou_pg = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-12)
        iou_pg = jnp.where(valid[:, None, None, None, :], iou_pg, 0.0)
        best_iou = jnp.max(iou_pg, axis=-1)        # (B, na, H, W)
        obj_mask = (best_iou <= ignore_thresh).astype(jnp.float32)
        bidx = jnp.arange(B)[:, None]
        for k, am in enumerate(anchor_mask):
            sel = valid & (best == am)          # (B, G)
            w_sel = sel.astype(jnp.float32)
            if rest and rest[0] is not None:
                w_sel = w_sel * rest[0]
            tx = gx * W - gi
            ty = gy * H - gj
            tw = jnp.log(jnp.maximum(
                gw * in_w / aw_all[am], 1e-9))
            th = jnp.log(jnp.maximum(
                gh * in_h / ah_all[am], 1e-9))
            scale = 2.0 - gw * gh
            pxg = px[bidx, k, gj, gi]
            pyg = py[bidx, k, gj, gi]
            pwg = pw[bidx, k, gj, gi]
            phg = ph[bidx, k, gj, gi]
            coord = (jnp.abs(pxg - tx) + jnp.abs(pyg - ty)
                     + jnp.abs(pwg - tw) + jnp.abs(phg - th)) * scale
            loss = loss + jnp.sum(coord * w_sel, axis=-1)
            obj_target = obj_target.at[bidx, k, gj, gi].max(
                sel.astype(jnp.float32))
            # class loss at assigned cells
            smooth = 1.0 / class_num if use_label_smooth else 0.0
            onehot = jax.nn.one_hot(gtl, class_num) * (1 - smooth) \
                + smooth / 2
            clg = cls_logit[bidx, k, :, gj, gi]
            ce = jnp.sum(
                jnp.maximum(clg, 0) - clg * onehot
                + jnp.log1p(jnp.exp(-jnp.abs(clg))), axis=-1)
            loss = loss + jnp.sum(ce * w_sel, axis=-1)
        # positives always contribute; non-ignored cells contribute as
        # negatives
        eff_mask = jnp.maximum(obj_mask, obj_target)
        obj_ce = (jnp.maximum(obj_logit, 0) - obj_logit * obj_target
                  + jnp.log1p(jnp.exp(-jnp.abs(obj_logit))))
        loss = loss + jnp.sum(obj_ce * eff_mask, axis=(1, 2, 3))
        return loss
    args = [as_tensor(x), as_tensor(gt_box), as_tensor(gt_label)]
    if gt_score is not None:
        args.append(as_tensor(gt_score))
    return apply(f, *args, name="yolo_loss")


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """reference: vision/ops.py matrix_nms (phi matrix_nms_kernel) —
    soft suppression via pairwise IoU decay. Host-side (dynamic output
    counts, like the reference CPU kernel)."""
    bx = np.asarray(raw(as_tensor(bboxes)), np.float32)
    sc = np.asarray(raw(as_tensor(scores)), np.float32)
    B, C, N = sc.shape
    outs, idxs, nums = [], [], []
    for b in range(B):
        rows = []
        ridx = []
        for c in range(C):
            if c == background_label:
                continue
            mask = sc[b, c] > score_threshold
            cand = np.where(mask)[0]
            if cand.size == 0:
                continue
            order = cand[np.argsort(-sc[b, c, cand])][:nms_top_k]
            boxes_c = bx[b, order]
            scores_c = sc[b, c, order]
            # pairwise IoU (upper triangle: against higher-scored)
            x1 = np.maximum(boxes_c[:, None, 0], boxes_c[None, :, 0])
            y1 = np.maximum(boxes_c[:, None, 1], boxes_c[None, :, 1])
            x2 = np.minimum(boxes_c[:, None, 2], boxes_c[None, :, 2])
            y2 = np.minimum(boxes_c[:, None, 3], boxes_c[None, :, 3])
            inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
            norm = 0.0 if normalized else 1.0
            area = ((boxes_c[:, 2] - boxes_c[:, 0] + norm)
                    * (boxes_c[:, 3] - boxes_c[:, 1] + norm))
            iou = inter / np.maximum(area[:, None] + area[None, :]
                                     - inter, 1e-12)
            n = len(order)
            tri = np.tril(iou, -1)
            # iou_max[j] = max IoU of (higher-scored) box j with boxes
            # above it — the compensation factor of the matrix-NMS paper
            iou_max = tri.max(axis=1) if n > 1 else np.zeros(n)
            if use_gaussian:
                decay = np.exp((iou_max ** 2 - tri ** 2)
                               / gaussian_sigma).min(
                    axis=1, initial=1.0, where=np.tril(
                        np.ones_like(tri, bool), -1))
            else:
                # decay[i] = min_j (1 - iou[i,j]) / (1 - iou_max[j]) over
                # higher-scored j (column-wise compensation)
                decay = ((1 - tri) / np.maximum(1 - iou_max[None, :],
                                                1e-12)).min(
                    axis=1, initial=1.0, where=np.tril(
                        np.ones_like(tri, bool), -1))
            dscore = scores_c * decay
            keep = dscore > post_threshold
            for i in np.where(keep)[0]:
                rows.append([c, dscore[i], *boxes_c[i]])
                ridx.append(order[i])
        rows = np.asarray(rows, np.float32).reshape(-1, 6)
        srt = np.argsort(-rows[:, 1])[:keep_top_k]
        outs.append(rows[srt])
        idxs.append(np.asarray(ridx, np.int64)[srt] if len(ridx) else
                    np.zeros((0,), np.int64))
        nums.append(len(srt))
    out = Tensor(jnp.asarray(np.concatenate(outs, axis=0)
                             if outs else np.zeros((0, 6), np.float32)),
                 _internal=True)
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.concatenate(idxs))
                          if idxs else jnp.zeros((0,), jnp.int64),
                          _internal=True))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32)),
                          _internal=True))
    return tuple(res) if len(res) > 1 else out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """reference: vision/ops.py deform_conv2d (phi deformable_conv) —
    DCNv1 (mask=None) / DCNv2: sample input at offset-shifted taps, then
    1x1-reduce with the kernel — expressed as gather + matmul so XLA maps
    the contraction onto the MXU."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    args = [as_tensor(x), as_tensor(offset), as_tensor(weight)]
    has_bias = bias is not None
    if has_bias:
        args.append(as_tensor(bias))
    has_mask = mask is not None
    if has_mask:
        args.append(as_tensor(mask))

    def f(xv, off, w, *rest):
        B, C, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        oh = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        K = kh * kw
        dg = deformable_groups
        off = off.reshape(B, dg, K, 2, oh, ow)
        base_y = (jnp.arange(oh) * st[0] - pd[0])[:, None]
        base_x = (jnp.arange(ow) * st[1] - pd[1])[None, :]
        ky = (jnp.arange(kh) * dl[0])[:, None]
        kx = (jnp.arange(kw) * dl[1])[None, :]
        # absolute sampling positions per kernel tap: (K, oh, ow)
        py = base_y[None] + jnp.repeat(
            ky.reshape(kh, 1, 1), kw, axis=0).reshape(K, 1, 1)
        px = (base_x[None] + jnp.tile(kx.reshape(1, kw), (kh, 1))
              .reshape(K, 1, 1))
        sy = py + off[:, :, :, 0]        # (B, dg, K, oh, ow)
        sx = px + off[:, :, :, 1]

        def bilinear(img, ys, xs):
            y0 = jnp.floor(ys)
            x0 = jnp.floor(xs)
            wy = ys - y0
            wx = xs - x0
            out = 0.0
            for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)),
                                (0, 1, (1 - wy) * wx),
                                (1, 0, wy * (1 - wx)),
                                (1, 1, wy * wx)):
                yy = y0 + dy
                xx = x0 + dx
                valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
                yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
                out = out + wgt * jnp.where(valid, img[yc, xc], 0.0)
            return out

        cpg = C // dg  # channels per deformable group

        def per_image(img, sy_i, sx_i):
            # img (C,H,W); sy_i (dg,K,oh,ow)
            def per_dg(chans, ys, xs):
                return jax.vmap(
                    lambda ch: jax.vmap(bilinear, in_axes=(None, 0, 0))(
                        ch, ys, xs))(chans)
            cols = jax.vmap(per_dg)(img.reshape(dg, cpg, H, W),
                                    sy_i, sx_i)      # (dg,cpg,K,oh,ow)
            return cols.reshape(C, K, oh, ow)
        cols = jax.vmap(per_image)(xv, sy, sx)        # (B,C,K,oh,ow)
        if has_mask:
            m = rest[-1].reshape(B, dg, K, oh, ow)
            m = jnp.repeat(m, cpg, axis=1)
            cols = cols * m
        # grouped contraction: (B, G, Cin_g*K, oh*ow) x (G, Cout_g, Cin_g*K)
        G = groups
        cols = cols.reshape(B, G, (C // G) * K, oh * ow)
        wg = w.reshape(G, Cout // G, Cin_g * kh * kw)
        out = jnp.einsum("bgkp,gok->bgop", cols, wg).reshape(
            B, Cout, oh, ow)
        if has_bias:
            out = out + rest[0].reshape(1, -1, 1, 1)
        return out
    return apply(f, *args, name="deform_conv2d")


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """reference: vision/ops.py distribute_fpn_proposals — route each RoI
    to its FPN level by scale. Host-side (dynamic per-level counts).
    With ``rois_num`` (per-image counts of the input), the returned
    per-level counts are per-image (length B), the layout roi_align's
    ``boxes_num`` expects."""
    rois = np.asarray(raw(as_tensor(fpn_rois)), np.float32)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        rn = np.asarray(raw(as_tensor(rois_num)), np.int64).reshape(-1)
    else:
        rn = np.asarray([len(rois)], np.int64)
    img_of = np.repeat(np.arange(len(rn)), rn)
    nlev = max_level - min_level + 1
    multi, nums = [], []
    restore = np.zeros(len(rois), np.int64)
    order = []
    for li in range(nlev):
        sel = lvl == min_level + li
        # per level, keep image-major order so per-image counts slice it
        idx = np.where(sel)[0]
        idx = idx[np.argsort(img_of[idx], kind="stable")]
        multi.append(Tensor(jnp.asarray(rois[idx]), _internal=True))
        per_img = np.asarray([(img_of[idx] == b).sum()
                              for b in range(len(rn))], np.int32)
        nums.append(Tensor(jnp.asarray(per_img), _internal=True))
        order.extend(idx.tolist())
    restore[np.asarray(order, np.int64)] = np.arange(len(rois))
    restore_t = Tensor(jnp.asarray(restore[:, None]), _internal=True)
    if rois_num is not None:
        return multi, restore_t, nums
    return multi, restore_t, None


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """reference: vision/ops.py generate_proposals (RPN) — decode anchor
    deltas, clip, filter small, NMS. Host-side like the reference CPU
    kernel."""
    sc = np.asarray(raw(as_tensor(scores)), np.float32)
    bd = np.asarray(raw(as_tensor(bbox_deltas)), np.float32)
    ims = np.asarray(raw(as_tensor(img_size)), np.float32)
    an = np.asarray(raw(as_tensor(anchors)), np.float32).reshape(-1, 4)
    var = np.asarray(raw(as_tensor(variances)), np.float32).reshape(-1, 4)
    B, A, H, W = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_scores, nums = [], [], []
    for b in range(B):
        s = sc[b].transpose(1, 2, 0).reshape(-1)
        d = bd[b].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s = s[order]
        d = d[order]
        a = an[order % len(an)] if len(an) != len(s) else an[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        v = var[order % len(var)] if len(var) != len(s) else var[order]
        cx = acx + d[:, 0] * v[:, 0] * aw
        cy = acy + d[:, 1] * v[:, 1] * ah
        w = aw * np.exp(np.clip(d[:, 2] * v[:, 2], None, 10))
        h = ah * np.exp(np.clip(d[:, 3] * v[:, 3], None, 10))
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=1)
        imh, imw = ims[b, 0], ims[b, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, imw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, imh - off)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep], s[keep]
        # greedy NMS
        sel = []
        idx = np.argsort(-s)
        while len(idx) and len(sel) < post_nms_top_n:
            i = idx[0]
            sel.append(i)
            if len(idx) == 1:
                break
            xx1 = np.maximum(boxes[i, 0], boxes[idx[1:], 0])
            yy1 = np.maximum(boxes[i, 1], boxes[idx[1:], 1])
            xx2 = np.minimum(boxes[i, 2], boxes[idx[1:], 2])
            yy2 = np.minimum(boxes[i, 3], boxes[idx[1:], 3])
            inter = (np.clip(xx2 - xx1 + off, 0, None)
                     * np.clip(yy2 - yy1 + off, 0, None))
            ai = ((boxes[i, 2] - boxes[i, 0] + off)
                  * (boxes[i, 3] - boxes[i, 1] + off))
            ar = ((boxes[idx[1:], 2] - boxes[idx[1:], 0] + off)
                  * (boxes[idx[1:], 3] - boxes[idx[1:], 1] + off))
            iou = inter / np.maximum(ai + ar - inter, 1e-12)
            idx = idx[1:][iou <= nms_thresh]
        all_rois.append(boxes[sel])
        all_scores.append(s[sel])
        nums.append(len(sel))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)), _internal=True)
    rscores = Tensor(jnp.asarray(np.concatenate(all_scores, 0)),
                     _internal=True)
    if return_rois_num:
        return rois, rscores, Tensor(
            jnp.asarray(np.asarray(nums, np.int32)), _internal=True)
    return rois, rscores


def read_file(filename, name=None):
    """reference: vision/ops.py read_file — raw bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)),
                  _internal=True)


def decode_jpeg(x, mode="unchanged", name=None):
    """reference: vision/ops.py decode_jpeg (nvjpeg kernel) — decode a
    uint8 JPEG byte tensor. Uses PIL when installed (no nvjpeg on TPU
    hosts); raises a clear error otherwise."""
    data = bytes(np.asarray(raw(as_tensor(x)), np.uint8).tobytes())
    try:
        from PIL import Image
        import io as _io
        img = Image.open(_io.BytesIO(data))
        if mode == "gray":
            img = img.convert("L")
        elif mode == "rgb":
            img = img.convert("RGB")
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[None]
        else:
            arr = arr.transpose(2, 0, 1)
        return Tensor(jnp.asarray(arr), _internal=True)
    except ImportError as e:
        raise RuntimeError(
            "decode_jpeg needs pillow on TPU hosts (no nvjpeg); "
            "`pip install pillow` in your own environment") from e


# ---------------- layer wrappers ----------------
class RoIAlign(Layer):
    """reference: vision/ops.py RoIAlign."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._a = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        o, s = self._a
        return roi_align(x, boxes, boxes_num, o, s)


class RoIPool(Layer):
    """reference: vision/ops.py RoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._a = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        o, s = self._a
        return roi_pool(x, boxes, boxes_num, o, s)


class PSRoIPool(Layer):
    """reference: vision/ops.py PSRoIPool."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._a = (output_size, spatial_scale)

    def forward(self, x, boxes, boxes_num):
        o, s = self._a
        return psroi_pool(x, boxes, boxes_num, o, s)


class DeformConv2D(Layer):
    """reference: vision/ops.py DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from .._core.tensor import Parameter
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        rng = np.random.default_rng(0)
        fan_in = in_channels // groups * ks[0] * ks[1]
        bound = (6.0 / max(1, fan_in + out_channels)) ** 0.5
        self.weight = Parameter(rng.uniform(
            -bound, bound,
            (out_channels, in_channels // groups, *ks)).astype(np.float32))
        self.bias = None if bias_attr is False else Parameter(
            np.zeros((out_channels,), np.float32))
        self._a = (stride, padding, dilation, deformable_groups, groups)

    def forward(self, x, offset, mask=None):
        st, pd, dl, dg, g = self._a
        return deform_conv2d(x, offset, self.weight, self.bias, st, pd,
                             dl, dg, g, mask)


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """reference: phi/kernels/impl/anchor_generator_kernel_impl.h — RPN
    anchor grid over a feature map. input: (N, C, H, W) (only H/W used).
    Returns (anchors (H, W, A, 4) xyxy, variances (H, W, A, 4)),
    A = len(aspect_ratios) * len(anchor_sizes)."""
    x = as_tensor(input)
    H, W = int(x.shape[2]), int(x.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    xs = np.arange(W, dtype=np.float32) * sw + offset * (sw - 1)
    ys = np.arange(H, dtype=np.float32) * sh + offset * (sh - 1)
    widths, heights = [], []
    area = sw * sh
    for ar in aspect_ratios:
        base_w = np.round(np.sqrt(area / ar))
        base_h = np.round(base_w * ar)
        for size in anchor_sizes:
            widths.append(size / sw * base_w)
            heights.append(size / sh * base_h)
    wv = np.asarray(widths, np.float32)
    hv = np.asarray(heights, np.float32)
    xc = np.broadcast_to(xs[None, :, None], (H, W, wv.size))
    yc = np.broadcast_to(ys[:, None, None], (H, W, wv.size))
    anchors = np.stack([xc - 0.5 * (wv - 1), yc - 0.5 * (hv - 1),
                        xc + 0.5 * (wv - 1), yc + 0.5 * (hv - 1)], -1)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          anchors.shape).copy()
    return (Tensor(jnp.asarray(anchors), _internal=True),
            Tensor(jnp.asarray(var), _internal=True))


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=-1, return_index=False,
                   rois_num=None, name=None):
    """reference: multiclass_nms3 op (legacy detection pipeline) — per-
    class greedy NMS then cross-class keep_top_k.

    bboxes (B, M, 4); scores (B, C, M). Returns (out (K, 6) rows
    [label, score, x1, y1, x2, y2], index (K, 1), nms_rois_num (B,)).
    Host-composed over the existing nms (same as the reference's CPU
    kernel)."""
    bv = np.asarray(raw(as_tensor(bboxes)))
    sv = np.asarray(raw(as_tensor(scores)))
    B, C, M = sv.shape
    rows, idxs, nums = [], [], []
    for b in range(B):
        cand = []
        for c in range(C):
            if c == background_label:
                continue
            keep = sv[b, c] > score_threshold
            if not keep.any():
                continue
            cls_idx = np.nonzero(keep)[0]
            order = np.argsort(-sv[b, c, cls_idx])
            cls_idx = cls_idx[order][:nms_top_k if nms_top_k > 0 else None]
            kept = np.asarray(nms(
                Tensor(jnp.asarray(bv[b, cls_idx]), _internal=True),
                iou_threshold=nms_threshold,
                scores=Tensor(jnp.asarray(sv[b, c, cls_idx]),
                              _internal=True)).numpy())
            for j in cls_idx[kept]:
                cand.append((c, float(sv[b, c, j]), j))
        cand.sort(key=lambda t: -t[1])
        if keep_top_k > 0:
            cand = cand[:keep_top_k]
        nums.append(len(cand))
        for c, s, j in cand:
            rows.append([float(c), s, *bv[b, j].tolist()])
            idxs.append(b * M + int(j))
    out = np.asarray(rows, np.float32).reshape(-1, 6)
    index = np.asarray(idxs, np.int32).reshape(-1, 1)
    res = (Tensor(jnp.asarray(out), _internal=True),
           Tensor(jnp.asarray(np.asarray(nums, np.int32)), _internal=True))
    if return_index:
        return res[0], Tensor(jnp.asarray(index), _internal=True), res[1]
    return res[0], res[1]


def yolo_box_head(x, anchors, class_num, name=None):
    """YOLO head activation: per anchor block of (5+class_num) channels,
    sigmoid on x/y/objectness/class logits and exp on w/h — the
    pre-decode step of the serving yolo pipeline.

    reference: paddle/phi/kernels/gpu/yolo_box_head_kernel.cu
    (YoloBoxHeadCudaKernel). jnp elementwise; runs on every backend (the
    reference kernel is GPU-only).
    """
    na = len(list(anchors)) // 2

    def f(pred):
        B, C, H, W = pred.shape
        p = pred.reshape(B, na, C // na, H, W)
        xy = jax.nn.sigmoid(p[:, :, 0:2])
        wh = jnp.exp(p[:, :, 2:4])
        rest = jax.nn.sigmoid(p[:, :, 4:])
        return jnp.concatenate([xy, wh, rest], axis=2).reshape(pred.shape)

    return apply(f, as_tensor(x), name="yolo_box_head")


def yolo_box_post(boxes0, boxes1, boxes2, image_shape, image_scale,
                  anchors0, anchors1, anchors2, class_num, conf_thresh,
                  downsample_ratio0, downsample_ratio1, downsample_ratio2,
                  clip_bbox=True, scale_x_y=1.0, nms_threshold=0.45,
                  name=None):
    """Decode three yolo_box_head outputs and run class-wise NMS.

    Per level: candidates with objectness >= conf_thresh decode to image
    coordinates (``pic = image_shape / image_scale``, anchors scaled by
    ``downsample_ratio * grid``), clipped to the image; per batch the
    candidates sort by (class, prob desc) and same-class boxes with
    IoU > nms_threshold are suppressed (score zeroed, kept in the output
    — reference PostNMS contract). Returns ``(out (total, 6)
    [label, score, x1, y1, x2, y2], nms_rois_num (B,))``.

    reference: paddle/phi/kernels/gpu/yolo_box_post_kernel.cu
    (YoloTensorParseKernel + PostNMS; clip_bbox/scale_x_y are accepted
    and unused there too). Host-side numpy: serving post-processing.
    """
    def _arr(t):
        return np.asarray(raw(as_tensor(t))).astype(np.float32)

    levels = [(_arr(boxes0), list(anchors0), downsample_ratio0),
              (_arr(boxes1), list(anchors1), downsample_ratio1),
              (_arr(boxes2), list(anchors2), downsample_ratio2)]
    shp, scl = _arr(image_shape), _arr(image_scale)
    batch = shp.shape[0]
    out_rows, nums = [], []
    for b in range(batch):
        pic_h = shp[b, 0] / scl[b, 0]
        pic_w = shp[b, 1] / scl[b, 1]
        dets = []   # (cls, obj, x1, y1, x2, y2, probs)
        for pred, anc, ds in levels:
            na = len(anc) // 2
            _, C, H, W = pred.shape
            p = pred[b].reshape(na, C // na, H, W)
            netw, neth = ds * W, ds * H
            for a in range(na):
                obj = p[a, 4]
                ys, xs = np.nonzero(obj >= conf_thresh)
                for yy, xx in zip(ys, xs):
                    o = obj[yy, xx]
                    cx = (p[a, 0, yy, xx] + xx) * pic_w / W
                    cy = (p[a, 1, yy, xx] + yy) * pic_h / H
                    ww = p[a, 2, yy, xx] * anc[2 * a] * pic_w / netw
                    hh = p[a, 3, yy, xx] * anc[2 * a + 1] * pic_h / neth
                    x1 = max(cx - ww / 2, 0.0)
                    y1 = max(cy - hh / 2, 0.0)
                    x2 = min(cx + ww / 2, pic_w - 1)
                    y2 = min(cy + hh / 2, pic_h - 1)
                    probs = p[a, 5:, yy, xx] * o
                    cls = int(np.argmax(probs)) if probs.size else -1
                    dets.append([cls, float(o), x1, y1, x2, y2,
                                 float(probs[cls]) if probs.size else 0.0])
        dets.sort(key=lambda d: (d[0], -d[6]))
        if dets:
            # one IoU matrix via the module's box_iou (single source of
            # IoU truth with nms/detection paths)
            bx = np.asarray([d[2:6] for d in dets], np.float32)
            iou = np.asarray(raw(box_iou(Tensor(bx), Tensor(bx))))
        for i in range(len(dets)):
            if dets[i][1] == 0:
                continue
            for j in range(i + 1, len(dets)):
                if dets[j][0] != dets[i][0]:
                    break
                if dets[j][1] == 0:
                    continue
                if iou[i, j] > nms_threshold:
                    dets[j][1] = 0.0
        for d in dets:
            out_rows.append([d[0], d[1], d[2], d[3], d[4], d[5]])
        nums.append(len(dets))
    if not out_rows:
        out_rows = [[0.0] * 6]
    return (Tensor(np.asarray(out_rows, np.float32)),
            Tensor(np.asarray(nums, np.int32)))


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Merge per-FPN-level proposals and keep the global top
    ``post_nms_top_n`` by score, re-grouped by batch image (the inverse
    of ``distribute_fpn_proposals``).

    ``multi_rois``: per-level (N_l, 4) boxes; ``multi_scores``: per-level
    (N_l,) scores; ``rois_num_per_level``: per-level (B,) int counts
    (the LoD-free batch encoding). Returns ``(fpn_rois (K, 4),
    rois_num (B,))`` with rows batch-major, score-sorted within batch.

    reference: phi/kernels/impl/collect_fpn_proposals_kernel_impl.h
    (stable score sort -> truncate -> stable batch-id sort).
    """
    rois = [np.asarray(raw(as_tensor(r))).reshape(-1, 4)
            for r in multi_rois]
    scores = [np.asarray(raw(as_tensor(s))).reshape(-1)
              for s in multi_scores]
    nlev = len(rois)
    if rois_num_per_level is None:
        # single-image convenience: everything is batch 0
        nums = [np.asarray([len(s)], np.int64) for s in scores]
    else:
        nums = [np.asarray(raw(as_tensor(n))).reshape(-1).astype(
            np.int64) for n in rois_num_per_level]
    batch = len(nums[0])
    recs = []          # (score, level, index_in_level, batch_id)
    for lv in range(nlev):
        bid = np.repeat(np.arange(batch), nums[lv])
        for j in range(len(scores[lv])):
            recs.append((float(scores[lv][j]), lv, j, int(bid[j])))
    order = sorted(range(len(recs)), key=lambda i: -recs[i][0])
    keep = min(post_nms_top_n, len(recs))
    top = [recs[i] for i in order[:keep]]
    top.sort(key=lambda r: r[3])            # stable: batch-major
    out = np.stack([rois[lv][idx] for _, lv, idx, _ in top]) if top \
        else np.zeros((0, 4), np.float32)
    counts = np.zeros((batch,), np.int32)
    for _, _, _, b in top:
        counts[b] += 1
    return Tensor(out.astype(np.float32)), Tensor(counts)
