"""Training callbacks (reference: python/paddle/hapi/callbacks.py, 1,459
lines — ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
ReduceLROnPlateau)."""
from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = callbacks if callbacks is not None else []
    cbks = cbks if isinstance(cbks, (list, tuple)) else [cbks]
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + list(cbks)
    if not any(isinstance(c, ModelCheckpoint) for c in cbks) and save_dir:
        cbks = list(cbks) + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = list(cbks) + [LRScheduler()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs,
                    "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __iter__(self):
        return iter(self.callbacks)

    def _call(self, name, *args):
        for c in self.callbacks:
            fn = getattr(c, name, None)
            if fn:
                fn(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_eval_begin(self, logs=None):
        self._call("on_eval_begin", logs)

    def on_eval_end(self, logs=None):
        self._call("on_eval_end", logs)

    def on_predict_begin(self, logs=None):
        self._call("on_predict_begin", logs)

    def on_predict_end(self, logs=None):
        self._call("on_predict_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, step, logs=None):
        self._call("on_train_batch_begin", step, logs)

    def on_train_batch_end(self, step, logs=None):
        self._call("on_train_batch_end", step, logs)

    def on_eval_batch_begin(self, step, logs=None):
        self._call("on_eval_batch_begin", step, logs)

    def on_eval_batch_end(self, step, logs=None):
        self._call("on_eval_batch_end", step, logs)

    def on_predict_batch_begin(self, step, logs=None):
        self._call("on_predict_batch_begin", step, logs)

    def on_predict_batch_end(self, step, logs=None):
        self._call("on_predict_batch_end", step, logs)


class Callback:
    """reference: hapi/callbacks.py Callback."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    """reference: hapi/callbacks.py ProgBarLogger."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple, np.ndarray)):
                parts.append(f"{k}: " + str([round(float(x), 4) for x in
                                             np.ravel(v)]))
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose == 2 and (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            print(f"epoch {epoch + 1} done in {dt:.1f}s - {self._fmt(logs)}")

    def on_eval_begin(self, logs=None):
        if self.verbose:
            print("Eval begin...")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval done - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and \
                (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference: hapi callbacks
    LRScheduler — by_step/by_epoch)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched
        opt = getattr(self.model, "_optimizer", None) if self.model else None
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -np.inf
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = np.inf
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        if self.baseline is not None and not self.better(cur, self.baseline):
            self.wait += 1
        elif self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
        if self.wait >= self.patience:
            if self.model:
                self.model.stop_training = True
            if self.verbose:
                print(f"Early stopping: no improvement in {self.monitor} "
                      f"for {self.patience} evals")


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.min_delta = min_delta
        self.mode = mode
        self.wait = 0
        self.cooldown_counter = 0
        self.best = None

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0])
        better = self.best is None or (
            cur > self.best + self.min_delta
            if (self.mode == "max" or (self.mode == "auto" and
                                       "acc" in self.monitor))
            else cur < self.best - self.min_delta)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if better:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = getattr(self.model, "_optimizer", None)
                if opt is not None:
                    try:
                        old = opt.get_lr()
                        new = max(old * self.factor, self.min_lr)
                        opt.set_lr(new)
                        if self.verbose:
                            print(f"ReduceLROnPlateau: lr {old} -> {new}")
                    except RuntimeError:
                        pass
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Log scalars to a simple jsonl (visualdl itself isn't in the image)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        import json
        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        import json
        if self._f and logs:
            rec = {"step": step}
            for k, v in logs.items():
                if isinstance(v, numbers.Number):
                    rec[k] = float(v)
            self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()
