"""Model summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from .._core.tensor import Tensor
from ..nn.layer.layers import Layer


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def register(layer, name):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) \
                else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = sum(p.size for p in l._parameters.values()
                           if p is not None)
            rows.append((name, type(l).__name__, shape, n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            register(sub, name)

    if input is not None:
        x = input
    elif input_size is not None:
        if isinstance(input_size, tuple) and input_size and \
                isinstance(input_size[0], (tuple, list)):
            x = [Tensor(np.zeros(s, np.float32)) for s in input_size]
        else:
            x = Tensor(np.zeros(tuple(input_size), np.float32))
    else:
        x = None
    try:
        if x is not None:
            was_training = net.training
            net.eval()
            net(*x) if isinstance(x, list) else net(x)
            if was_training:
                net.train()
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(p.size) for p in net.parameters())
    trainable = sum(int(p.size) for p in net.parameters()
                    if not p.stop_gradient)
    if rows:
        w = max(len(r[0]) for r in rows) + 2
        print(f"{'Layer':{w}s}{'Type':22s}{'Output Shape':20s}{'Params':>12s}")
        print("-" * (w + 54))
        for name, t, shape, n in rows:
            print(f"{name:{w}s}{t:22s}{str(shape):20s}{n:>12,d}")
        print("-" * (w + 54))
    print(f"Total params: {total:,d}")
    print(f"Trainable params: {trainable:,d}")
    print(f"Non-trainable params: {total - trainable:,d}")
    return {"total_params": total, "trainable_params": trainable}
