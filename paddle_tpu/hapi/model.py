"""paddle.Model high-level API (reference: python/paddle/hapi/model.py —
Model:1472, fit:2200, train_batch:1625, _run_one_epoch:2772).

TPU-native execution: train/eval batches run through jit-compiled fused steps
(paddle_tpu.jit.TrainStep/EvalStep) — the reference's DynamicGraphAdapter
per-op dispatch is replaced by one XLA program per step. Set
``use_compiled=False`` to fall back to pure eager (tape) execution.
"""
from __future__ import annotations

import os
import warnings
from typing import List, Optional

import numpy as np

from .._core.tensor import Tensor
from .._core import autograd as ag
from ..nn.layer.layers import Layer
from ..metric.metrics import Metric
from ..framework.io import save as fsave, load as fload
from ..jit.api import TrainStep, EvalStep, InputSpec
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    """reference: hapi/model.py:1472."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self.stop_training = False
        self._train_step = None
        self._eval_step = None
        self._use_compiled = True

    # ---- configuration ----
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, use_compiled=True):
        """reference: model.py prepare. ``amp_configs``: dict with 'level'
        ('O1'/'O2'), 'dtype', 'init_loss_scaling', ... (reference:
        model.py _check_amp_configs)."""
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer) or
                                     callable(loss)):
            raise TypeError("loss must be a Layer or callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu.metric."
                                "Metric")
        self._use_compiled = use_compiled
        self._scaler = None
        self._amp_level = "O0"
        if amp_configs:
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            from ..amp import decorate as amp_decorate, GradScaler
            from .._core import dtype as dtypes
            self._amp_level = amp_configs.get("level", "O1")
            dtype = amp_configs.get("dtype", "float16")
            if self._amp_level == "O2":
                amp_decorate(self.network, level="O2", dtype=dtype)
            if dtypes.convert_dtype(dtype) == dtypes.float16 and \
                    self._amp_level in ("O1", "O2"):
                self._scaler = GradScaler(
                    init_loss_scaling=amp_configs.get(
                        "init_loss_scaling", 2.0 ** 15))
        self._train_step = None
        self._eval_step = None
        self._accumulate = 1
        return self

    # ---- single-batch APIs ----
    def train_batch(self, inputs, labels=None, update=True):
        """reference: model.py:1625."""
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        self.network.train()
        if not update and self._use_compiled:
            # manual grad accumulation requested: the compiled step owns
            # parameter state, so hand control back to eager mode (for the
            # compiled equivalent use fit(accumulate_grad_batches=N))
            warnings.warn(
                "train_batch(update=False) switches this Model to eager "
                "execution; prefer fit(accumulate_grad_batches=N) for the "
                "compiled path")
            self._sync_if_needed()
            self._use_compiled = False
            self._train_step = None
        if self._use_compiled:
            if self._train_step is None:
                self._train_step = TrainStep(
                    self.network, self._loss, self._optimizer,
                    scaler=self._scaler,
                    accumulate_steps=getattr(self, "_accumulate", 1),
                    return_outputs=True)
            loss, outs = self._train_step(tuple(inputs), tuple(labels))
            metrics = []
            for m in self._metrics:
                m_in = m.compute(*outs, *labels)
                metrics.append(m.update(m_in))
            return self._pack_loss_metrics(loss, metrics)
        # eager path
        outputs = self.network(*inputs)
        outs = _to_list(outputs)
        loss = self._loss(*outs, *labels)
        if self._scaler is not None:
            self._scaler.scale(loss).backward()
            if update:
                self._scaler.step(self._optimizer)
                self._scaler.update()
                self._optimizer.clear_grad()
        else:
            loss.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m_in = m.compute(*outs, *labels)
            metrics.append(m.update(m_in))
        return self._pack_loss_metrics(loss, metrics)

    def eval_batch(self, inputs, labels=None):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        self.network.eval()
        self._sync_if_needed()
        if self._eval_step is None:
            self._eval_step = EvalStep(self.network)
        out = self._eval_step(*inputs)
        outs = _to_list(out)
        losses = None
        if self._loss is not None:
            with ag.no_grad():
                losses = self._loss(*outs, *labels)
        metrics = []
        for m in self._metrics:
            m_in = m.compute(*outs, *labels)
            metrics.append(m.update(m_in))
        return self._pack_loss_metrics(losses, metrics) if losses is not None \
            else metrics

    def predict_batch(self, inputs):
        inputs = _to_list(inputs)
        self.network.eval()
        self._sync_if_needed()
        if self._eval_step is None:
            self._eval_step = EvalStep(self.network)
        out = self._eval_step(*inputs)
        return [o.numpy() for o in _to_list(out)]

    def _pack_loss_metrics(self, loss, metrics):
        lv = [np.asarray(loss.numpy()).reshape(1)] if isinstance(
            loss, Tensor) else [np.asarray(loss).reshape(1)]
        if self._metrics:
            return lv, metrics
        return lv

    def _sync_if_needed(self):
        if self._train_step is not None:
            self._train_step.sync_to_model()
            self._train_step.sync_from_model()

    # ---- fit / evaluate / predict ----
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """reference: model.py:2200."""
        from ..io.dataloader import DataLoader
        from ..io.dataset import Dataset

        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        self._accumulate = max(1, int(accumulate_grad_batches))
        if self._accumulate > 1 and self._train_step is not None and \
                self._train_step.accumulate_steps != self._accumulate:
            self._sync_if_needed()
            self._train_step = None

        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose,
                                metrics=self._metrics_name())
        self.stop_training = False
        cbks.on_train_begin()
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                ins, lbs = self._split_batch(batch)
                res = self.train_batch(ins, lbs)
                logs = self._update_logs(res)
                cbks.on_train_batch_end(step, logs)
                if num_iters is not None and step + 1 >= num_iters:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _from_fit=True)
                cbks.on_eval_end(eval_logs)
        self._sync_if_needed()
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None,
                 _from_fit=False):
        from ..io.dataloader import DataLoader
        from ..io.dataset import Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        for m in self._metrics:
            m.reset()
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            ins, lbs = self._split_batch(batch)
            res = self.eval_batch(ins, lbs)
            if isinstance(res, tuple):
                losses.append(res[0][0])
            elif isinstance(res, list) and res and isinstance(
                    res[0], np.ndarray):
                losses.append(res[0])
            if num_iters is not None and step + 1 >= num_iters:
                break
        if losses:
            logs["loss"] = [float(np.mean([np.ravel(l)[0]
                                           for l in losses]))]
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            for n, v in zip(names, vals):
                logs[n] = v
        if verbose:
            print("Eval:", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        from ..io.dataloader import DataLoader
        from ..io.dataset import Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, predict=True)
            outs = self.predict_batch(ins)
            outputs.append(outs)
        # transpose list-of-batches -> list-of-outputs
        n_out = len(outputs[0]) if outputs else 0
        res = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            res = [np.concatenate(r, axis=0) for r in res]
        return res

    def _split_batch(self, batch, predict=False):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            n_in = len(self._inputs) if self._inputs else (
                len(batch) - (len(self._labels) if self._labels else 1))
            if predict and len(batch) <= n_in:
                return batch, []
            if n_in <= 0:
                n_in = max(len(batch) - 1, 1)
            return batch[:n_in], batch[n_in:]
        return [batch], []

    def _update_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            logs["loss"] = [float(np.ravel(l)[0]) for l in losses]
            for m, v in zip(self._metrics, metrics):
                names = m.name() if isinstance(m.name(), list) else [m.name()]
                vals = np.ravel(v).tolist()
                for n, val in zip(names, vals):
                    logs[n] = val
        else:
            logs["loss"] = [float(np.ravel(l)[0]) for l in res]
        return logs

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    # ---- state ----
    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def state_dict(self):
        self._sync_if_needed()
        return self.network.state_dict()

    def save(self, path, training=True):
        """reference: model.py save — <path>.pdparams + <path>.pdopt."""
        self._sync_if_needed()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(fload(opt_path))
        self._train_step = None
        self._eval_step = None

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtype)
