"""Pallas TPU fused kernels: RMSNorm(+residual), SwiGLU, RoPE, and
decode-time block attention.

TPU-native counterparts of the reference's fused GPU kernels
(reference: paddle/phi/kernels/fusion/fused_layernorm_kernel.cu,
fused_bias_act_kernel.cu, fused_rope_kernel.cu,
block_multi_head_attention_kernel.cu). Each is a single HBM pass with fp32
on-chip math and a hand-written VJP, so the backward is also one fused
pass instead of XLA's recomputed chain.

All kernels run in interpret mode on CPU for tests (``set_interpret``) and
on real TPU otherwise; ``available()`` mirrors flash_attention's gate.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

from .flash_attention import available, set_interpret  # shared gate

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

from . import flash_attention as _fa


def _interp():
    return _fa._interpret_mode()


# ---------------- fused RMSNorm (+ residual) ----------------
def _rms_fwd_kernel(x_ref, w_ref, o_ref, r_ref, *, eps, has_res):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o_ref[...] = (x * rstd * w_ref[...].astype(jnp.float32)).astype(
        o_ref.dtype)
    r_ref[...] = rstd.astype(jnp.float32)


def _rms_norm_fwd(x, w, eps, block_rows):
    n, h = x.shape
    br = min(block_rows, n)
    grid = (pl.cdiv(n, br),)
    out, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps, has_res=False),
        grid=grid,
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)],
        interpret=_interp(),
    )(x, w)
    return out, rstd


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dwp_ref, *, eps,
                    n_rows, block_rows):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    rstd = rstd_ref[...]
    if n_rows % block_rows:
        # zero padded rows: their garbage would leak into the dw row-sum
        i = pl.program_id(0)
        rows = i * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, x.shape, 0)
        x = jnp.where(rows < n_rows, x, 0.0)
        g = jnp.where(rows < n_rows, g, 0.0)
        rstd = jnp.where(rows[:, :1] < n_rows, rstd, 0.0)
    xhat = x * rstd
    wg = g * w
    # dx = rstd * (wg - xhat * mean(wg * xhat))
    m = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (wg - xhat * m)).astype(dx_ref.dtype)
    # per-block dw partial, padded to an (8, h) tile: Mosaic requires the
    # second-to-last block dim divisible by 8 (a (1, h) block fails to
    # lower on hardware); row 0 carries the sum, rows 1-7 are zero
    part = jnp.sum(g * xhat, axis=0, keepdims=True)          # (1, h)
    row = jax.lax.broadcasted_iota(jnp.int32, (8, part.shape[-1]), 0)
    dwp_ref[...] = jnp.where(row == 0, part, 0.0)[None]


def _rms_norm_bwd(eps, block_rows, res, g):
    x, w, rstd = res
    n, h = x.shape
    br = min(block_rows, n)
    nb = pl.cdiv(n, br)
    dx, dwp = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps, n_rows=n,
                          block_rows=br),
        grid=(nb,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((h,), lambda i: (0,)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                   jax.ShapeDtypeStruct((nb, 8, h), jnp.float32)],
        interpret=_interp(),
    )(x, w, rstd, g)
    return dx, jnp.sum(dwp, axis=(0, 1)).astype(w.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm_2d(x, w, eps, block_rows):
    out, _ = _rms_norm_fwd(x, w, eps, block_rows)
    return out


def _rms_norm_2d_fwd(x, w, eps, block_rows):
    out, rstd = _rms_norm_fwd(x, w, eps, block_rows)
    return out, (x, w, rstd)


_rms_norm_2d.defvjp(_rms_norm_2d_fwd, _rms_norm_bwd)


def rms_norm(x, w, eps: float = 1e-6, residual=None, block_rows: int = 256):
    """Fused RMSNorm over the last dim; optional residual add fused into
    the same pass (returns (out, x+residual) then, matching the
    reference's fused_rms_norm contract)."""
    if residual is not None:
        x = x + residual  # XLA fuses this add into the kernel's HBM read
        return rms_norm(x, w, eps, block_rows=block_rows), x
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    out = _rms_norm_2d(x2, w, float(eps), block_rows)
    return out.reshape(shape)


# ---------------- fused SwiGLU ----------------
def _swiglu_fwd_kernel(g_ref, u_ref, o_ref):
    g = g_ref[...].astype(jnp.float32)
    u = u_ref[...].astype(jnp.float32)
    o_ref[...] = (jax.nn.silu(g) * u).astype(o_ref.dtype)


def _swiglu_bwd_kernel(g_ref, u_ref, d_ref, dg_ref, du_ref):
    g = g_ref[...].astype(jnp.float32)
    u = u_ref[...].astype(jnp.float32)
    d = d_ref[...].astype(jnp.float32)
    sig = jax.nn.sigmoid(g)
    silu = g * sig
    dsilu = sig * (1.0 + g * (1.0 - sig))
    dg_ref[...] = (d * u * dsilu).astype(dg_ref.dtype)
    du_ref[...] = (d * silu).astype(du_ref.dtype)


def _swiglu_2d(g, u, block_rows):
    n, h = g.shape
    br = min(block_rows, n)
    return pl.pallas_call(
        _swiglu_fwd_kernel,
        grid=(pl.cdiv(n, br),),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), g.dtype),
        interpret=_interp(),
    )(g, u)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _swiglu(g, u, block_rows):
    return _swiglu_2d(g, u, block_rows)


def _swiglu_fwd_rule(g, u, block_rows):
    return _swiglu_2d(g, u, block_rows), (g, u)


def _swiglu_bwd_rule(block_rows, res, d):
    g, u = res
    n, h = g.shape
    br = min(block_rows, n)
    dg, du = pl.pallas_call(
        _swiglu_bwd_kernel,
        grid=(pl.cdiv(n, br),),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0))] * 3,
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0))] * 2,
        out_shape=[jax.ShapeDtypeStruct((n, h), g.dtype),
                   jax.ShapeDtypeStruct((n, h), u.dtype)],
        interpret=_interp(),
    )(g, u, d)
    return dg, du


_swiglu.defvjp(_swiglu_fwd_rule, _swiglu_bwd_rule)


def swiglu(g, u, block_rows: int = 256):
    """Fused silu(g) * u (reference: fused_bias_act_kernel.cu swiglu path);
    one HBM pass fwd, one bwd."""
    shape = g.shape
    out = _swiglu(g.reshape(-1, shape[-1]), u.reshape(-1, shape[-1]),
                  block_rows)
    return out.reshape(shape)


# ---------------- fused RoPE (q and k in one launch) ----------------
def _rope_kernel(x1_ref, x2_ref, cos_ref, sin_ref, o1_ref, o2_ref, *,
                 sign):
    # pure elementwise on pre-split halves: Mosaic rejects both lane-dim
    # slices at `half` (gather rule) and lane-splitting in-kernel
    # reshapes ("unsupported shape cast") — round-2's packed kernel hit
    # both on real hardware while CPU interpret mode hid it. The halves
    # and the per-head table tiling are prepared outside, in XLA.
    x1 = x1_ref[...].astype(jnp.float32)
    x2 = x2_ref[...].astype(jnp.float32)
    c = cos_ref[...].astype(jnp.float32)
    s = sin_ref[...].astype(jnp.float32) * sign
    o1_ref[...] = (x1 * c - x2 * s).astype(o1_ref.dtype)
    o2_ref[...] = (x2 * c + x1 * s).astype(o2_ref.dtype)


def _rope_apply(x, cos, sin, sign, block_seq):
    """x: (B, S, H, D) -> rotated; cos/sin: (S, D/2) half tables."""
    B, S, H, D = x.shape
    bs = min(block_seq, S)
    half = D // 2
    x1 = x[..., :half].reshape(B, S, H * half)
    x2 = x[..., half:].reshape(B, S, H * half)
    ct = jnp.tile(cos, (1, H))                   # (S, H*half)
    st = jnp.tile(sin, (1, H))
    o1, o2 = pl.pallas_call(
        functools.partial(_rope_kernel, sign=sign),
        grid=(B, pl.cdiv(S, bs)),
        in_specs=[pl.BlockSpec((1, bs, H * half), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((1, bs, H * half), lambda b, i: (b, i, 0)),
                  pl.BlockSpec((bs, H * half), lambda b, i: (i, 0)),
                  pl.BlockSpec((bs, H * half), lambda b, i: (i, 0))],
        out_specs=[pl.BlockSpec((1, bs, H * half),
                                lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, bs, H * half),
                                lambda b, i: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((B, S, H * half), x.dtype),
                   jax.ShapeDtypeStruct((B, S, H * half), x.dtype)],
        interpret=_interp(),
    )(x1, x2, ct, st)
    return jnp.concatenate(
        [o1.reshape(B, S, H, half), o2.reshape(B, S, H, half)], axis=-1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _rope_qk(q, k, cos, sin, block_seq):
    return (_rope_apply(q, cos, sin, 1.0, block_seq),
            _rope_apply(k, cos, sin, 1.0, block_seq))


def _rope_qk_fwd(q, k, cos, sin, block_seq):
    return _rope_qk(q, k, cos, sin, block_seq), (cos, sin)


def _rope_qk_bwd(block_seq, res, g):
    cos, sin = res
    dq, dk = g
    # rotation is orthogonal: the VJP is rotation by -theta
    return (_rope_apply(dq, cos, sin, -1.0, block_seq),
            _rope_apply(dk, cos, sin, -1.0, block_seq), None, None)


_rope_qk.defvjp(_rope_qk_fwd, _rope_qk_bwd)


def rope_qk(q, k, cos, sin, block_seq: int = 256):
    """Fused neox-style RoPE on q and k (reference:
    fused_rope_kernel.cu). cos/sin: (S, D/2) half tables or (S, D)
    repeated-half tables; q (B,S,H,D), k (B,S,HK,D)."""
    half = q.shape[-1] // 2
    if cos.shape[-1] == 2 * half:   # repeated-half layout: halves equal
        cos, sin = cos[:, :half], sin[:, :half]
    return _rope_qk(q, k, cos.astype(jnp.float32),
                    sin.astype(jnp.float32), block_seq)


# ---------------- decode-time block attention (KV cache) ----------------
def _decode_softmax_step(q, k, v, cache_len, o_ref, acc, m_sc, l_sc,
                         *, scale, block_k, k_scale=None, v_scale=None,
                         num_valid=None):
    """Shared online-softmax step for the decode kernels (contiguous and
    paged): one (H_rep, D) query block against one (block_k, D) K/V block
    at sequence offset ki*block_k, masked by cache_len.

    ``k_scale``/``v_scale``: optional per-row DEQUANT scalars for int8
    pages (the cachekv-int8 tier) — dequantization happens here in VMEM,
    so the HBM reads stay 1 byte/element.

    ``num_valid``: optional traced count of LIVE column blocks for this
    grid row (the ragged paged grid: ``ceil(cache_len / block_k)``).
    Blocks past it are fully masked — their contribution is an exact
    no-op (p == 0, alpha == 1) — so the step early-outs: compute is
    skipped under ``pl.when`` and the output is finalized at the row's
    OWN last live block instead of the grid extent. The caller's index
    map must clamp exhausted iterations to a previously fetched block so
    no DMA is issued for them (Ragged Paged Attention, arxiv
    2604.15464). ``None`` keeps the dense behavior: every block live,
    finalize at ``num_programs(1) - 1``."""
    ki = pl.program_id(1)
    last = (pl.num_programs(1) if num_valid is None else num_valid) - 1

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_sc[...] = jnp.full_like(m_sc, -jnp.inf)
        l_sc[...] = jnp.zeros_like(l_sc)

    def _accum():
        kk, vv = k, v
        if k_scale is not None:
            kk = (kk.astype(jnp.float32) * k_scale).astype(q.dtype)
        if v_scale is not None:
            vv = (vv.astype(jnp.float32) * v_scale).astype(q.dtype)
        # zero possibly-padded cache rows: 0 * NaN would poison p @ v
        vrows = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, vv.shape, 0)
        vv = jnp.where(vrows < cache_len, vv, jnp.zeros_like(vv))
        s = jax.lax.dot_general(
            q, kk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (H_rep, bk)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < cache_len, s, _fa.DEFAULT_MASK_VALUE)
        m_prev = m_sc[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        p = jnp.where(cols < cache_len, p, 0.0)
        l_sc[...] = alpha * l_sc[...] + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), m_prev.shape)
        acc[...] = acc[...] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[...] = m_new

    if num_valid is None:
        _accum()
    else:
        pl.when(ki <= last)(_accum)

    @pl.when(ki == last)
    def _done():
        l = l_sc[:, :1]
        o_ref[0] = (acc[...] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, acc, m_sc, l_sc,
                   *, scale, block_k):
    # len_ref is the WHOLE (B*HK,) SMEM vector (Mosaic rejects rank-1
    # blocks of size 1 that aren't lane-multiples — caught by the AOT
    # lowering guard); index it by grid row
    _decode_softmax_step(q_ref[0], k_ref[0], v_ref[0],
                         len_ref[pl.program_id(0)],
                         o_ref, acc, m_sc, l_sc, scale=scale,
                         block_k=block_k)


def _decode_kernel_qrow(q_ref, k_ref, v_ref, ks_ref, vs_ref, len_ref,
                        o_ref, acc, m_sc, l_sc, *, scale, block_k):
    """int8-cache variant with PER-ROW dequant scales (each cached token
    row carries its own scale — self-calibrating, no static calibration
    pass): scales ride a (block_k, 1) VMEM block and broadcast over D."""
    _decode_softmax_step(q_ref[0], k_ref[0], v_ref[0],
                         len_ref[pl.program_id(0)],
                         o_ref, acc, m_sc, l_sc, scale=scale,
                         block_k=block_k, k_scale=ks_ref[0],
                         v_scale=vs_ref[0])


def decode_attention(q, k_cache, v_cache, cache_len, *, scale=None,
                     block_k: int = 512, k_dequant_rows=None,
                     v_dequant_rows=None):
    """Single-token flash attention against a padded KV cache (reference:
    block_multi_head_attention_kernel.cu decode path).

    q: (B, H, D) the current position's query
    k_cache/v_cache: (B, S_max, HK, D); positions >= cache_len are masked
    cache_len: scalar or (B,) int32 valid-length(s)
    returns (B, H, D). GQA/MQA handled by head-group mapping, no repeat.

    ``k/v_dequant_rows`` (cachekv-int8): (B, S_max, HK) fp32 PER-ROW
    dequant scales for int8 caches — each cached token row carries its
    own scale; dequantization happens in VMEM so HBM reads stay
    1 byte/element.
    """
    B, H, D = q.shape
    S = k_cache.shape[1]
    HK = k_cache.shape[2]
    assert H % HK == 0
    rep = H // HK
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    bk = min(block_k, S)
    cache_len = jnp.broadcast_to(jnp.asarray(cache_len, jnp.int32), (B,))
    if (k_dequant_rows is None) != (v_dequant_rows is None):
        raise ValueError(
            "decode_attention: k_dequant_rows and v_dequant_rows must be "
            "passed together — int8 caches quantize both K and V")
    quant = k_dequant_rows is not None

    # (B, S, HK, D) -> (B*HK, S, D); q -> (B*HK, rep, D): one grid row per
    # kv-head group so GQA costs no HBM duplication
    kt = k_cache.transpose(0, 2, 1, 3).reshape(B * HK, S, D)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(B * HK, S, D)
    qt = q.reshape(B, HK, rep, D).reshape(B * HK, rep, D)
    lens = jnp.repeat(cache_len, HK)

    in_specs = [
        pl.BlockSpec((1, rep, D), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, bk, D), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, bk, D), lambda i, j: (i, j, 0)),
    ]
    inputs = [qt, kt, vt]
    if quant:
        def rows(sc):   # (B, S, HK) -> (B*HK, S, 1)
            return jnp.asarray(sc, jnp.float32).transpose(
                0, 2, 1).reshape(B * HK, S, 1)
        in_specs += [pl.BlockSpec((1, bk, 1), lambda i, j: (i, j, 0)),
                     pl.BlockSpec((1, bk, 1), lambda i, j: (i, j, 0))]
        inputs += [rows(k_dequant_rows), rows(v_dequant_rows)]
        kernel = functools.partial(_decode_kernel_qrow, scale=s,
                                   block_k=bk)
    else:
        kernel = functools.partial(_decode_kernel, scale=s, block_k=bk)
    # whole-vector SMEM block (Mosaic rank-1 rule: block dim must equal
    # the array dim or be a lane multiple); kernels index by grid row
    in_specs.append(pl.BlockSpec(
        (B * HK,), lambda i, j: (0,),
        memory_space=pltpu.SMEM if _PALLAS_OK else None))
    inputs.append(lens)

    out = pl.pallas_call(
        kernel,
        grid=(B * HK, pl.cdiv(S, bk)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rep, D), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * HK, rep, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, D), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
        ],
        interpret=_interp(),
    )(*inputs)
    return out.reshape(B, HK, rep, D).reshape(B, H, D)


# ---------------- paged decode attention (block tables) ----------------
def _paged_decode_kernel(bt_ref, q_ref, k_ref, v_ref, len_ref, o_ref,
                         acc, m_sc, l_sc, *, scale, page):
    """Same online-softmax as _decode_kernel; k/v blocks arrive via the
    scalar-prefetched block-table index map (vLLM-style indirection), so
    the block refs carry (1, 1, page, D) with the page-pool dims leading.
    len_ref/scale refs are whole SMEM vectors indexed by grid row (the
    Mosaic rank-1 block rule — AOT lowering guard)."""
    _decode_softmax_step(q_ref[0], k_ref[0, 0], v_ref[0, 0],
                         len_ref[pl.program_id(0)],
                         o_ref, acc, m_sc, l_sc, scale=scale,
                         block_k=page)


def _paged_decode_kernel_q(bt_ref, q_ref, k_ref, v_ref, len_ref, ks_ref,
                           vs_ref, o_ref, acc, m_sc, l_sc, *, scale,
                           page):
    """int8-page variant: per-row dequant scales ride SMEM; pages stay
    1 byte/element in HBM and dequantize in VMEM."""
    i = pl.program_id(0)
    _decode_softmax_step(q_ref[0], k_ref[0, 0], v_ref[0, 0], len_ref[i],
                         o_ref, acc, m_sc, l_sc, scale=scale,
                         block_k=page, k_scale=ks_ref[i],
                         v_scale=vs_ref[i])


def paged_decode_attention(q, k_pages, v_pages, block_tables, cache_len, *,
                           scale=None, k_dequant_scale=None,
                           v_dequant_scale=None):
    """Single-token flash attention over a PAGED KV cache (reference:
    block_multi_head_attention_kernel.cu + vLLM paged attention).

    q:            (B, H, D) current queries
    k/v_pages:    (num_pages, HK, page_size, D) page pool
    block_tables: (B, pages_per_seq) int32 page ids (-1 pad allowed)
    cache_len:    scalar or (B,) valid lengths
    returns (B, H, D). The page id feeds the kernel's BlockSpec index map
    via scalar prefetch — the gather over pages happens in the memory
    pipeline, not as a materialized contiguous copy.

    ``k/v_dequant_scale`` (cachekv-int8): per-head ``(HK,)`` or
    per-sequence-per-head ``(B, HK)`` fp32 dequant scales for int8
    pages; dequantization happens inside the kernel, so HBM reads stay
    1 byte/element — the paged long-context bandwidth win.
    """
    B, H, D = q.shape
    HK, page = k_pages.shape[1], k_pages.shape[2]
    assert H % HK == 0
    rep = H // HK
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    ppseq = block_tables.shape[1]
    cache_len = jnp.broadcast_to(jnp.asarray(cache_len, jnp.int32), (B,))

    kp = k_pages.transpose(1, 0, 2, 3)       # (HK, P, page, D)
    vp = v_pages.transpose(1, 0, 2, 3)
    qt = q.reshape(B, HK, rep, D).reshape(B * HK, rep, D)
    lens = jnp.repeat(cache_len, HK)
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)  # clamp -1
    if (k_dequant_scale is None) != (v_dequant_scale is None):
        raise ValueError(
            "paged_decode_attention: k_dequant_scale and v_dequant_scale "
            "must be passed together — int8 pages quantize both K and V")
    quant = k_dequant_scale is not None

    def _rows(sc):
        # grid row i = b*HK + h: (HK,) tiles over B; (B, HK) flattens
        sc = jnp.asarray(sc, jnp.float32)
        return (jnp.tile(sc, B) if sc.ndim == 1
                else sc.reshape(B * HK))

    in_specs = [
        pl.BlockSpec((1, rep, D), lambda i, j, bt_: (i, 0, 0)),
        pl.BlockSpec((1, 1, page, D),
                     lambda i, j, bt_: (i % HK, bt_[i // HK, j], 0, 0)),
        pl.BlockSpec((1, 1, page, D),
                     lambda i, j, bt_: (i % HK, bt_[i // HK, j], 0, 0)),
        pl.BlockSpec((B * HK,), lambda i, j, bt_: (0,),
                     memory_space=pltpu.SMEM if _PALLAS_OK else None),
    ]
    inputs = [bt, qt, kp, vp, lens]
    if quant:
        for _ in range(2):
            in_specs.append(pl.BlockSpec(
                (B * HK,), lambda i, j, bt_: (0,),
                memory_space=pltpu.SMEM if _PALLAS_OK else None))
        inputs += [_rows(k_dequant_scale), _rows(v_dequant_scale)]
        kernel = functools.partial(_paged_decode_kernel_q, scale=s,
                                   page=page)
    else:
        kernel = functools.partial(_paged_decode_kernel, scale=s,
                                   page=page)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * HK, ppseq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rep, D), lambda i, j, bt_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, D), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
        ],
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * HK, rep, D), q.dtype),
        interpret=_interp(),
    )(*inputs)
    return out.reshape(B, HK, rep, D).reshape(B, H, D)
