"""Paged decode attention over block-table-indexed KV pools.

The serving-side op of the paged KV cache (paddle_tpu/serving/): K/V
live in a global page pool ``(num_pages, page_size, nkv, hd)`` per layer
and each request owns an ordered list of page ids (its block table), so
HBM is sized by TOKENS IN FLIGHT instead of ``batch * longest_request``
(reference: block_multi_head_attention_kernel.cu; TPU-native design:
Ragged Paged Attention, arxiv 2604.15464 / vLLM block tables).

Two implementations with IDENTICAL semantics:

- :func:`paged_attention_kernel` — Pallas TPU kernel: the block table
  feeds the K/V BlockSpec index maps via scalar prefetch, so the page
  gather happens in the memory pipeline (no materialized contiguous
  copy). The grid is RAGGED: a second scalar-prefetched vector of
  per-row page counts clamps the index maps (no DMA past a row's last
  live page) and early-outs the softmax step, so a mixed-length batch
  pays ``Σ ceil(len_i/page)`` pages of attention work instead of
  ``B * ppseq``. int8 pages carry PER-ROW dequant scales (the
  cachekv-int8 tier of the dense path) and dequantize in VMEM — HBM
  reads stay 1 byte/element.
- :func:`paged_attention_reference` — pure ``lax`` gather + the exact
  attention composition of ``models/generate._attn_with_cache`` (same
  einsums, f32 accumulation, -1e30 masking), so tier-1 CPU tests
  exercise the same numerics the dense decode path produces.

:func:`paged_attention` dispatches: kernel on real TPU (or when forced
via ``use_kernel=True`` — interpret mode in tests), reference elsewhere.

TENSOR-PARALLEL serving (ISSUE 7) runs this op UNCHANGED, per shard:
inside the engine's ``shard_map`` each shard holds ``nkv/tp`` heads of
every page (``(P, page, nkv/tp, hd)`` local pools, the same page ids
everywhere) and its own ``nh/tp`` query heads. Attention softmax is
per-head, so the kernel needs NO cross-shard communication — the grid
simply has ``B * nkv/tp`` rows instead of ``B * nkv``, and the GQA
``rep = H // HK`` grouping still holds because query and kv heads shard
along the same head-group boundaries (``models/llama.
validate_serving_tp`` guarantees the divisibility; the ``nkv < tp``
replication path presents exactly one kv head per shard). Lowering of
the sharded program is gated by ``tools/aot_validate.py --config
serving-tp``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .flash_attention import available, set_interpret  # noqa: F401 — gate
from . import flash_attention as _fa
from . import fused as _fused

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


def gather_pages(pages: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Materialize each request's pages in logical-position order:
    pages (P, page, ...) + block_tables (B, ppseq) -> (B, ppseq*page,
    ...). Slot ``s`` of the result is logical token position ``s`` —
    the contiguous-cache view of the paged storage (reference fallback;
    the TPU kernel never materializes this copy)."""
    B, ppseq = block_tables.shape
    page = pages.shape[1]
    g = jnp.take(pages, block_tables.reshape(-1), axis=0)
    return g.reshape((B, ppseq * page) + pages.shape[2:])


def paged_attention_reference(q, k_pages, v_pages, block_tables, lengths,
                              *, scale=None, ks_pages=None, vs_pages=None):
    """Pure-lax paged decode attention (CPU tier-1 semantics anchor).

    q:            (B, H, D) single-token queries
    k/v_pages:    (P, page, HK, D) page pools
    block_tables: (B, ppseq) int32 page ids (logical-position order)
    lengths:      (B,) valid lengths INCLUDING the current token
    ks/vs_pages:  (P, page, HK) per-row dequant scales for int8 pools

    The math after the gather is kept OP-FOR-OP identical to
    ``models/generate._attn_with_cache`` so a paged decode is
    token-identical to the dense-cache decode it replaces.
    """
    B, H, D = q.shape
    ck = gather_pages(k_pages, block_tables)      # (B, S, HK, D)
    cv = gather_pages(v_pages, block_tables)
    if (ks_pages is None) != (vs_pages is None):
        raise ValueError(
            "paged_attention: ks_pages and vs_pages must be passed "
            "together — int8 pools quantize both K and V")
    if ks_pages is not None:
        k_rows = gather_pages(ks_pages, block_tables)   # (B, S, HK)
        v_rows = gather_pages(vs_pages, block_tables)
        ck = (ck.astype(jnp.float32) * k_rows[..., None]).astype(q.dtype)
        cv = (cv.astype(jnp.float32) * v_rows[..., None]).astype(q.dtype)
    nkv = ck.shape[2]
    if nkv != H:
        ck = jnp.repeat(ck, H // nkv, axis=2)
        cv = jnp.repeat(cv, H // nkv, axis=2)
    qf = q[:, None]                                # (B, 1, H, D)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf.astype(jnp.float32),
                   ck.astype(jnp.float32))
    # keep the default path literally `/ sqrt(hd)` — bit-parity with the
    # dense `_attn_with_cache` composition is the tier-1 gate
    s = s * scale if scale is not None else s / math.sqrt(D)
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (B,))
    kpos = lax.broadcasted_iota(jnp.int32, s.shape, 3)
    qpos = (lengths[:, None, None, None] - 1)
    s = jnp.where(kpos <= qpos, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv)
    return o[:, 0]                                 # (B, H, D)


# ------------- Pallas RAGGED kernel (per-row-scale int8 tier) -------------
#
# The grid's column extent is the SLOT extent (ppseq pages — static
# shapes), but per-row work is LENGTH-PROPORTIONAL (Ragged Paged
# Attention, arxiv 2604.15464): a scalar-prefetched per-row page count
# drives (a) the K/V index maps, which CLAMP exhausted iterations to the
# row's last live page — the pipeline sees an unchanged block index and
# issues no new DMA — and (b) an early-out in the softmax step, which
# skips the dots and finalizes the output at the row's own last page.
# A mixed-length batch therefore streams Σ ceil(len_i/page) pages of KV
# instead of B * ppseq.

def _paged_kernel(bt_ref, cnt_ref, q_ref, k_ref, v_ref, len_ref, o_ref,
                  acc, m_sc, l_sc, *, scale, page):
    """One (rep, D) query block vs one page of K/V; pages arrive via the
    scalar-prefetched block-table index maps, so grid column j IS logical
    page j of this request (online-softmax offset j*page) while j is
    live; cnt_ref (the per-row page count) early-outs the rest. len_ref
    is the whole (B*HK,) SMEM vector (Mosaic rank-1 block rule)."""
    i = pl.program_id(0)
    _fused._decode_softmax_step(q_ref[0], k_ref[0, 0], v_ref[0, 0],
                                len_ref[i],
                                o_ref, acc, m_sc, l_sc, scale=scale,
                                block_k=page, num_valid=cnt_ref[i])


def _paged_kernel_rowq(bt_ref, cnt_ref, q_ref, k_ref, v_ref, ks_ref,
                       vs_ref, len_ref, o_ref, acc, m_sc, l_sc, *,
                       scale, page):
    """int8-page variant: PER-ROW dequant scales ride (1, 1, page, 1)
    VMEM blocks gathered by the same block-table index map as K/V, so
    each cached token row dequantizes with its own scale in VMEM (the
    self-calibrating cachekv-int8 tier of the dense decode kernel)."""
    i = pl.program_id(0)
    _fused._decode_softmax_step(q_ref[0], k_ref[0, 0], v_ref[0, 0],
                                len_ref[i],
                                o_ref, acc, m_sc, l_sc, scale=scale,
                                block_k=page, k_scale=ks_ref[0, 0],
                                v_scale=vs_ref[0, 0],
                                num_valid=cnt_ref[i])


def paged_attention_kernel(q, k_pages, v_pages, block_tables, lengths, *,
                           scale=None, ks_pages=None, vs_pages=None):
    """Pallas ragged paged decode attention; same contract (and the same
    results, bit for bit — masked pages were exact no-ops) as
    :func:`paged_attention_reference` (pool layout (P, page, HK, D),
    per-row int8 scales (P, page, HK)), but per-row attention work is
    sized by ``ceil(length/page)`` instead of the slot extent."""
    if not _PALLAS_OK:
        raise RuntimeError(
            "paged_attention_kernel: jax.experimental.pallas is "
            "unavailable — use paged_attention() (or use_kernel=False) "
            "for the pure-lax fallback")
    B, H, D = q.shape
    P, page, HK = k_pages.shape[0], k_pages.shape[1], k_pages.shape[2]
    assert H % HK == 0
    rep = H // HK
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    ppseq = block_tables.shape[1]
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (B,))

    # pool -> (HK, P, page, D): kv-head leads so one grid row serves a
    # whole GQA head group with no HBM duplication
    kp = k_pages.transpose(2, 0, 1, 3)
    vp = v_pages.transpose(2, 0, 1, 3)
    qt = q.reshape(B, HK, rep, D).reshape(B * HK, rep, D)
    lens = jnp.repeat(lengths, HK)
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)  # clamp -1
    # per-ROW live page counts (broadcast over the HK grid rows of each
    # request); >= 1 so every row finalizes its output block
    cnt = jnp.clip(-(-lengths // page), 1, ppseq).astype(jnp.int32)
    cnt = jnp.repeat(cnt, HK)

    if (ks_pages is None) != (vs_pages is None):
        raise ValueError(
            "paged_attention: ks_pages and vs_pages must be passed "
            "together — int8 pools quantize both K and V")
    quant = ks_pages is not None

    def _page_idx(i, j, bt_, cnt_):
        # clamp exhausted iterations to the row's LAST live page: the
        # block index is unchanged vs the previous iteration, so the
        # pipeline skips the copy — the ragged grid's DMA early-out
        return bt_[i // HK, jnp.minimum(j, cnt_[i] - 1)]

    in_specs = [
        pl.BlockSpec((1, rep, D), lambda i, j, bt_, cnt_: (i, 0, 0)),
        pl.BlockSpec((1, 1, page, D),
                     lambda i, j, bt_, cnt_:
                     (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
        pl.BlockSpec((1, 1, page, D),
                     lambda i, j, bt_, cnt_:
                     (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
    ]
    inputs = [bt, cnt, qt, kp, vp]
    if quant:
        def _scl(sc):   # (P, page, HK) -> (HK, P, page, 1)
            return jnp.asarray(sc, jnp.float32).transpose(
                2, 0, 1).reshape(HK, P, page, 1)
        in_specs += [
            pl.BlockSpec((1, 1, page, 1),
                         lambda i, j, bt_, cnt_:
                         (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
            pl.BlockSpec((1, 1, page, 1),
                         lambda i, j, bt_, cnt_:
                         (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
        ]
        inputs += [_scl(ks_pages), _scl(vs_pages)]
        kernel = functools.partial(_paged_kernel_rowq, scale=s, page=page)
    else:
        kernel = functools.partial(_paged_kernel, scale=s, page=page)
    in_specs.append(pl.BlockSpec(
        (B * HK,), lambda i, j, bt_, cnt_: (0,),
        memory_space=pltpu.SMEM))
    inputs.append(lens)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * HK, ppseq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rep, D),
                               lambda i, j, bt_, cnt_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, D), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * HK, rep, D), q.dtype),
        interpret=_fa._interpret_mode(),
    )(*inputs)
    return out.reshape(B, HK, rep, D).reshape(B, H, D)


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    scale=None, ks_pages=None, vs_pages=None,
                    use_kernel=None):
    """Paged decode attention: Pallas kernel on real TPU (or when forced
    — interpret mode in tests), pure-lax gather fallback elsewhere so
    tier-1 CPU runs exercise dense-decode-identical numerics."""
    if use_kernel is None:
        try:
            use_kernel = jax.devices()[0].platform == "tpu"
        except Exception:
            use_kernel = False
    if use_kernel:
        return paged_attention_kernel(
            q, k_pages, v_pages, block_tables, lengths, scale=scale,
            ks_pages=ks_pages, vs_pages=vs_pages)
    return paged_attention_reference(
        q, k_pages, v_pages, block_tables, lengths, scale=scale,
        ks_pages=ks_pages, vs_pages=vs_pages)
