"""Pallas TPU flash attention.

TPU-native replacement for the reference's FlashAttention CUDA kernels
(reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu,
flash_attn_grad_kernel.cu; python surface
python/paddle/nn/functional/flash_attention.py:195).

Blockwise online-softmax forward saving per-row LSE; two-pass backward
(dkv sweep, dq sweep) recomputing probabilities from LSE — the standard
FlashAttention-2 decomposition, laid out for the MXU: 128-aligned q/k blocks,
fp32 accumulation, grid iterated sequentially so VMEM scratch carries state
across k-blocks.

Layout: (batch, seq, heads, head_dim) at the API, reshaped to
(batch*heads, seq, head_dim) for the kernels.
"""
from __future__ import annotations

import functools
import math
import os

import numpy as np
import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

_INTERPRET = False  # set True to run kernels on CPU for tests


def set_interpret(v: bool):
    global _INTERPRET
    _INTERPRET = v


_FORCE_COMPILE = False   # AOT lowering guard: emit Mosaic even off-TPU


class force_compiled_lowering:
    """Context manager for the AOT lowering guard (tests/test_pallas_
    lowering.py): pretend the backend is a TPU so every kernel takes the
    COMPILED (Mosaic) lowering path under ``jax.export(platforms=
    ['tpu'])`` on a CPU host. Never use for execution — only lowering."""

    def __enter__(self):
        global _FORCE_COMPILE
        self._old = _FORCE_COMPILE
        _FORCE_COMPILE = True
        return self

    def __exit__(self, *exc):
        global _FORCE_COMPILE
        _FORCE_COMPILE = self._old
        return False


def _interpret_mode() -> bool:
    """True when kernels must run in pallas interpret mode: forced by
    set_interpret, or whenever the backend is not a real TPU (CPU pallas
    lowering supports interpret only)."""
    if _FORCE_COMPILE:
        return False
    if _INTERPRET:
        return True
    try:
        # platform, not backend name: the axon PJRT tunnel's backend is
        # named "axon" but its devices ARE TPU chips (compiled mode)
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def available() -> bool:
    if not _PALLAS_OK:
        return False
    if _INTERPRET:
        return True
    try:
        # platform (not backend name): the axon PJRT tunnel registers a
        # backend named "axon" whose devices are TPU chips
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


DEFAULT_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _block_iota(block_q, block_k, dim):
    return jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), dim)


def _zero_pad_rows(x, start, valid_len):
    """Zero rows >= valid_len (block-local). Out-of-bounds Pallas reads are
    undefined (NaN in interpret mode) and 0*NaN = NaN would leak through the
    matmul accumulators, so padded inputs must be zeroed at load time."""
    rows = start + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(rows < valid_len, x, jnp.zeros_like(x))


# ---------------- forward ----------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        # skip blocks strictly above the diagonal
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                # (block_q, d) bf16 ok:
        k = k_ref[0]                                # MXU takes bf16 inputs
        v = v_ref[0]                                # with fp32 accumulate
        if seq_k % block_k:
            k = _zero_pad_rows(k, ki * block_k, seq_k)
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk) f32
        if causal:
            rows = qi * block_q + _block_iota(block_q, block_k, 0)
            cols = ki * block_k + _block_iota(block_q, block_k, 1)
            s = jnp.where(rows >= cols, s, DEFAULT_MASK_VALUE)
        if seq_k % block_k:
            # last k-block is padded: Pallas out-of-bounds reads are
            # undefined, so mask columns >= seq_k out of the softmax
            cols = ki * block_k + _block_iota(block_q, block_k, 1)
            s = jnp.where(cols < seq_k, s, DEFAULT_MASK_VALUE)
        m_prev = m_ref[:]                            # (bq, 128)
        m_cur = jnp.max(s, axis=1, keepdims=True)    # (bq, 1)
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)              # (bq, 128)
        p = jnp.exp(s - m_new[:, :1])                # (bq, bk)
        l_new = alpha * l_ref[:] + jnp.broadcast_to(
            jnp.sum(p, axis=1, keepdims=True), m_prev.shape)
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new
        l_ref[:] = l_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:] + jnp.log(jnp.where(l == 0.0, 1.0, l))
        lse_ref[0] = lse[:, :1].astype(jnp.float32)


def _fwd(q, k, v, scale, causal, block_q, block_k, out_dtype=None,
         kv_rep=1):
    """out_dtype: dtype of the normalized output (default q.dtype). The
    ring-attention partial merge passes fp32 so per-chunk partials are
    not rounded to bf16 before the cross-chunk combine.

    kv_rep: GQA — q rows are (B*H) while k/v rows are (B*H/kv_rep); the
    kv BlockSpec index map divides the grid's batch-head index, so the
    kernel reads each kv head group once with NO repeated HBM copy (same
    trick as the decode kernel in fused.py)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(sk, block_k)
    grid = (bh, nq, nk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, r=kv_rep: (b // r, j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j, r=kv_rep: (b // r, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d),
                                 out_dtype if out_dtype is not None
                                 else q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k, v)
    return out, lse


# ---------------- backward ----------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale, causal, block_q, block_k, seq_q, seq_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (qi * block_q + block_q - 1) >= (ki * block_k)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                    # (block_q, 1)
        delta = delta_ref[0]                # (block_q, 1)
        if seq_q % block_q:
            q = _zero_pad_rows(q, qi * block_q, seq_q)
            do = _zero_pad_rows(do, qi * block_q, seq_q)
            lse = _zero_pad_rows(lse, qi * block_q, seq_q)
            delta = _zero_pad_rows(delta, qi * block_q, seq_q)
        if seq_k % block_k:
            k = _zero_pad_rows(k, ki * block_k, seq_k)
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + _block_iota(block_q, block_k, 0)
            cols = ki * block_k + _block_iota(block_q, block_k, 1)
            s = jnp.where(rows >= cols, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)                # (bq, bk) f32
        if seq_q % block_q or seq_k % block_k:
            # padded q-rows would contaminate the dk/dv row-sums (their
            # lse/do are out-of-bounds garbage); padded k-cols only produce
            # garbage in dk/dv rows that get cropped, but zero them too so
            # inf/NaN can't leak through the accumulator
            rows = qi * block_q + _block_iota(block_q, block_k, 0)
            cols = ki * block_k + _block_iota(block_q, block_k, 1)
            p = jnp.where((rows < seq_q) & (cols < seq_k), p, 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = (ki * block_k) <= (qi * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        if seq_k % block_k:
            k = _zero_pad_rows(k, ki * block_k, seq_k)
            v = _zero_pad_rows(v, ki * block_k, seq_k)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + _block_iota(block_q, block_k, 0)
            cols = ki * block_k + _block_iota(block_q, block_k, 1)
            s = jnp.where(rows >= cols, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale)
        if seq_k % block_k:
            # padded k-cols would contaminate the dq column-sums
            cols = ki * block_k + _block_iota(block_q, block_k, 1)
            ds = jnp.where(cols < seq_k, ds, 0.0)
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd(scale, causal, block_q, block_k, block_q_bwd, block_k_bwd,
         res, g):
    q, k, v, out, lse = res
    do = g
    bh, sq, d = q.shape
    sk = k.shape[1]
    # bwd blocks tune independently of fwd (the dkv pass re-reads q/do
    # per k block and the dq pass re-reads k/v per q block — different
    # reuse patterns than the fwd)
    bq = min(block_q_bwd or block_q, sq)
    bk = min(block_k_bwd or block_k, sk)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(sk, bk)
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (bh, sq, 1)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, seq_q=sq, seq_k=sk),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret_mode(),
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, seq_k=sk),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret_mode(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_bhsd(q, k, v, scale, causal, block_q, block_k,
                block_q_bwd=None, block_k_bwd=None):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k,
                    block_q_bwd=None, block_k_bwd=None):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


_FLASH_WINNER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
    "FLASH_WINNER.json")
_TUNED_BLOCKS = None  # cache; False = checked and absent/invalid


def _tuned_blocks():
    """Adopt the hardware-measured tiling winner (tools/flash_bench.py
    writes FLASH_WINNER.json when a config beats the built-in default by
    >2% fwd+bwd) so a live retune reaches every default-blocks caller
    without a code change. Validated whole: malformed, out-of-range, or
    stale (>14 d) records are ignored."""
    global _TUNED_BLOCKS
    if _TUNED_BLOCKS is not None:
        return _TUNED_BLOCKS or None
    _TUNED_BLOCKS = False
    if os.environ.get("PADDLE_TPU_FLASH_TUNED", "1") == "0":
        return None
    try:
        import json
        import time
        with open(_FLASH_WINNER) as f:
            rec = json.load(f)
        cfg = rec.get("cfg")
        if (isinstance(cfg, list) and len(cfg) == 4
                and all(c is None or (isinstance(c, int)
                                      and 128 <= c <= 4096 and c % 128 == 0)
                        for c in cfg)
                and cfg[0] and cfg[1]
                and time.time() - rec.get("recorded_unix", 0) < 14 * 86400):
            _TUNED_BLOCKS = tuple(cfg)
    except Exception:
        pass
    return _TUNED_BLOCKS or None


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, block_q_bwd=None, block_k_bwd=None):
    """(B, S, H, D) flash attention. Raw jax arrays in/out (op-layer wraps
    it into the Tensor/autograd surface). block_q_bwd/block_k_bwd
    override the backward kernels' tiling (None = same as forward).
    With all four block args left at None, a hardware-measured tiling
    from FLASH_WINNER.json is adopted when present (else 512/1024)."""
    if block_q is None and block_k is None and block_q_bwd is None \
            and block_k_bwd is None:
        tuned = _tuned_blocks()
        if tuned is not None:
            block_q, block_k, block_q_bwd, block_k_bwd = tuned
    if block_q is None:
        block_q = 512
    if block_k is None:
        block_k = 1024
    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if hk != h:  # GQA/MQA: repeat kv heads
        assert h % hk == 0
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash_bhsd(qt, kt, vt, s, causal, block_q, block_k,
                      block_q_bwd, block_k_bwd)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
