"""Fused Pallas serving kernels (ISSUE 11): the decode hot loop's
remaining kernel seams collapsed into single launches.

Decode on the serving tower is HBM-bandwidth-bound (PERF_NOTES), so
every intermediate a step writes to HBM and re-reads is tokens/s lost.
Two fusions live here (the third — the fused page gather/scatter — is a
plain donated XLA program in ``serving/paged_cache._pool_move``):

- :func:`fused_paged_decode_attention` — the ragged paged decode kernel
  (ops/pallas/paged_attention.py) grown to apply the query's RoPE
  ROTATION IN-KERNEL next to the existing in-VMEM int8 KV dequant: the
  unfused step materializes the rotated q to HBM and re-reads it in the
  attention kernel (plus, on the reference path, a dequanted fp copy of
  the KV); fused, q streams in unrotated with its per-row cos/sin rows
  and both the rotation and the dequant happen in VMEM — two HBM
  round-trips removed per layer per step (reference: the rope+attention
  fusion of masked_multihead_attention_kernel.cu; TPU design: Ragged
  Paged Attention, arxiv 2604.15464 + the XLA operator-fusion analysis,
  PAPERS.md).
- :func:`flash_chunk_attention` — a flash-attention kernel for the
  MULTI-TOKEN serving programs (chunked/continuation prefill AND the
  speculative verify forward), reusing flash_attention.py's online-
  softmax structure with the ragged ``kstart``/``rpos`` machinery of
  ``models/generate._attn_with_cache``: per-row first-valid-column
  masks plus per-QUERY causal positions, with int8 temp-cache rows
  dequantized in VMEM. One kernel, two consumers —
  ``paged_prefill_chunk`` and ``paged_verify_forward`` — so the two
  programs cannot drift.

Every kernel follows the paged_attention fallback pattern: a pure-lax
reference with op-for-op the math of the unfused path (bit-identical on
CPU tier-1), and the Pallas kernel runs in interpret mode off-TPU
(``set_interpret``) so parity tests exercise the real kernel body under
``JAX_PLATFORMS=cpu``. Gates: fused output is TOKEN-IDENTICAL to the
unfused path PER TIER — fused-fp vs unfused-fp, fused-int8 vs
unfused-int8, fused-int4 vs unfused-int4, fused-w8kv8 vs unfused-w8kv8
— single-chip and under ``shard_map`` on the tp mesh
(tests/test_lowbit_decode.py); Mosaic lowering is gated by
``tools/aot_validate.py --config serving-lowbit``.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .flash_attention import available, set_interpret  # noqa: F401 — gate
from . import flash_attention as _fa
from . import fused as _fused
from . import paged_attention as _pa

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


def rotate_half(x: jax.Array) -> jax.Array:
    """``concat([-x2, x1])`` of the last dim's halves — the full-width
    RoPE companion operand. Computed OUTSIDE the kernel (a sign flip +
    lane permutation XLA folds into the producing matmul's epilogue):
    Mosaic rejects lane-dim slices at ``D/2`` inside a kernel (the
    ``fused._rope_kernel`` lesson), so the kernel receives ``x`` and
    ``rotate_half(x)`` and computes ``x*cos + rotate_half(x)*sin`` as
    pure full-width elementwise math. The sign flip is exact in every
    dtype and ``a + (-b)*s == a - b*s`` op-for-op in IEEE, so the
    formulation reproduces ``generate._rope_rows``'s values — up to the
    compiler's fma contraction of the mul/add pair (last-ulp), which is
    why the KERNEL path's gate is token-identity per tier (the repo's
    standing contract for every Pallas decode kernel) while the
    REFERENCE path, which uses the literal ``_rope_rows`` expression,
    is bit-identical to the unfused composition."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _rope_full_tables(cos_row, sin_row):
    """(B, D/2) per-row half tables -> (B, D) full-width f32 tables
    (halves repeated — the rotate_half formulation's layout)."""
    c = jnp.asarray(cos_row, jnp.float32)
    s = jnp.asarray(sin_row, jnp.float32)
    return (jnp.concatenate([c, c], axis=-1),
            jnp.concatenate([s, s], axis=-1))


def rotate_q_reference(q, cos_row, sin_row):
    """Reference q rotation — op-for-op ``generate._rope_rows`` at T=1:
    q (B, H, D), cos/sin_row (B, D/2) gathered at each row's position.
    f32 elementwise math, cast back to q's dtype."""
    x1, x2 = jnp.split(q, 2, axis=-1)
    c = jnp.asarray(cos_row, jnp.float32)[:, None, :]
    s = jnp.asarray(sin_row, jnp.float32)[:, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(q.dtype)


def fused_paged_decode_reference(q, cos_row, sin_row, k_pages, v_pages,
                                 block_tables, lengths, *, scale=None,
                                 ks_pages=None, vs_pages=None):
    """Pure-lax reference of the fused decode op: the exact unfused
    composition — ``_rope_rows``-identical rotation, then
    :func:`~paddle_tpu.ops.pallas.paged_attention.
    paged_attention_reference` — so the fused reference path is
    BIT-identical to the unfused reference path by construction."""
    qr = rotate_q_reference(q, cos_row, sin_row)
    return _pa.paged_attention_reference(
        qr, k_pages, v_pages, block_tables, lengths, scale=scale,
        ks_pages=ks_pages, vs_pages=vs_pages)


# --------- fused dequant + RoPE + ragged paged decode attention ---------

def _fused_paged_kernel(bt_ref, cnt_ref, q_ref, qh_ref, ct_ref, st_ref,
                        k_ref, v_ref, len_ref, o_ref, acc, m_sc, l_sc,
                        *, scale, page):
    """The ragged ``_paged_kernel`` with the q RoPE rotation fused in:
    q arrives UNROTATED with its rotate_half companion and full-width
    per-row cos/sin tables; the rotation is f32 elementwise in VMEM
    (identical values to the XLA ``_rope_rows`` it replaces), then the
    shared online-softmax step runs unchanged."""
    i = pl.program_id(0)
    qrot = (q_ref[0].astype(jnp.float32) * ct_ref[0]
            + qh_ref[0].astype(jnp.float32) * st_ref[0]).astype(
                q_ref.dtype)
    _fused._decode_softmax_step(qrot, k_ref[0, 0], v_ref[0, 0],
                                len_ref[i],
                                o_ref, acc, m_sc, l_sc, scale=scale,
                                block_k=page, num_valid=cnt_ref[i])


def _fused_paged_kernel_rowq(bt_ref, cnt_ref, q_ref, qh_ref, ct_ref,
                             st_ref, k_ref, v_ref, ks_ref, vs_ref,
                             len_ref, o_ref, acc, m_sc, l_sc, *, scale,
                             page):
    """int8-page variant: per-row dequant scales ride the same
    block-table-indexed VMEM blocks as K/V, so rotation AND dequant both
    happen in VMEM — HBM reads stay 1 byte/element and the rotated q
    never round-trips."""
    i = pl.program_id(0)
    qrot = (q_ref[0].astype(jnp.float32) * ct_ref[0]
            + qh_ref[0].astype(jnp.float32) * st_ref[0]).astype(
                q_ref.dtype)
    _fused._decode_softmax_step(qrot, k_ref[0, 0], v_ref[0, 0],
                                len_ref[i],
                                o_ref, acc, m_sc, l_sc, scale=scale,
                                block_k=page, k_scale=ks_ref[0, 0],
                                v_scale=vs_ref[0, 0],
                                num_valid=cnt_ref[i])


def fused_paged_decode_kernel(q, cos_row, sin_row, k_pages, v_pages,
                              block_tables, lengths, *, scale=None,
                              ks_pages=None, vs_pages=None):
    """Pallas fused RoPE + (dequant +) ragged paged decode attention.

    q:            (B, H, D) UNROTATED single-token queries
    cos/sin_row:  (B, D/2) rope table rows at each row's position
    k/v_pages:    (P, page, HK, D) pools; ks/vs_pages (P, page, HK)
                  per-row int8 dequant scales
    block_tables: (B, ppseq) int32; lengths: (B,) incl. current token

    Same ragged grid, GQA head-group mapping and online-softmax step as
    :func:`~paddle_tpu.ops.pallas.paged_attention.
    paged_attention_kernel`; the only addition is the in-VMEM rotation,
    whose values match the unfused XLA rotation exactly."""
    if not _PALLAS_OK:
        raise RuntimeError(
            "fused_paged_decode_kernel: jax.experimental.pallas is "
            "unavailable — use fused_paged_decode_attention() for the "
            "pure-lax fallback")
    B, H, D = q.shape
    P, page, HK = k_pages.shape[0], k_pages.shape[1], k_pages.shape[2]
    assert H % HK == 0
    rep = H // HK
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    ppseq = block_tables.shape[1]
    lengths = jnp.broadcast_to(jnp.asarray(lengths, jnp.int32), (B,))

    kp = k_pages.transpose(2, 0, 1, 3)
    vp = v_pages.transpose(2, 0, 1, 3)

    def _rows(x):   # (B, H, D) -> (B*HK, rep, D)
        return x.reshape(B, HK, rep, D).reshape(B * HK, rep, D)

    qt = _rows(q)
    qh = _rows(rotate_half(q))
    cf, sf = _rope_full_tables(cos_row, sin_row)          # (B, D) f32
    ct = _rows(jnp.broadcast_to(cf[:, None, :], (B, H, D)))
    st = _rows(jnp.broadcast_to(sf[:, None, :], (B, H, D)))
    lens = jnp.repeat(lengths, HK)
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    cnt = jnp.clip(-(-lengths // page), 1, ppseq).astype(jnp.int32)
    cnt = jnp.repeat(cnt, HK)

    if (ks_pages is None) != (vs_pages is None):
        raise ValueError(
            "fused_paged_decode: ks_pages and vs_pages must be passed "
            "together — int8 pools quantize both K and V")
    quant = ks_pages is not None

    def _page_idx(i, j, bt_, cnt_):
        # clamp exhausted iterations to the row's last live page (the
        # ragged DMA early-out, same as the unfused kernel)
        return bt_[i // HK, jnp.minimum(j, cnt_[i] - 1)]

    qspec = pl.BlockSpec((1, rep, D), lambda i, j, bt_, cnt_: (i, 0, 0))
    in_specs = [
        qspec, qspec, qspec, qspec,
        pl.BlockSpec((1, 1, page, D),
                     lambda i, j, bt_, cnt_:
                     (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
        pl.BlockSpec((1, 1, page, D),
                     lambda i, j, bt_, cnt_:
                     (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
    ]
    inputs = [bt, cnt, qt, qh, ct, st, kp, vp]
    if quant:
        def _scl(sc):   # (P, page, HK) -> (HK, P, page, 1)
            return jnp.asarray(sc, jnp.float32).transpose(
                2, 0, 1).reshape(HK, P, page, 1)
        in_specs += [
            pl.BlockSpec((1, 1, page, 1),
                         lambda i, j, bt_, cnt_:
                         (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
            pl.BlockSpec((1, 1, page, 1),
                         lambda i, j, bt_, cnt_:
                         (i % HK, _page_idx(i, j, bt_, cnt_), 0, 0)),
        ]
        inputs += [_scl(ks_pages), _scl(vs_pages)]
        kernel = functools.partial(_fused_paged_kernel_rowq, scale=s,
                                   page=page)
    else:
        kernel = functools.partial(_fused_paged_kernel, scale=s,
                                   page=page)
    in_specs.append(pl.BlockSpec(
        (B * HK,), lambda i, j, bt_, cnt_: (0,),
        memory_space=pltpu.SMEM))
    inputs.append(lens)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * HK, ppseq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rep, D),
                               lambda i, j, bt_, cnt_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, D), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
            pltpu.VMEM((rep, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * HK, rep, D), q.dtype),
        interpret=_fa._interpret_mode(),
    )(*inputs)
    return out.reshape(B, HK, rep, D).reshape(B, H, D)


def fused_paged_decode_attention(q, cos_row, sin_row, k_pages, v_pages,
                                 block_tables, lengths, *, scale=None,
                                 ks_pages=None, vs_pages=None,
                                 use_kernel=None):
    """Dispatcher (the paged_attention pattern): Pallas kernel on real
    TPU or when forced (interpret mode in tests), pure-lax reference —
    bit-identical to the unfused reference composition — elsewhere."""
    if use_kernel is None:
        try:
            use_kernel = jax.devices()[0].platform == "tpu"
        except Exception:
            use_kernel = False
    if use_kernel:
        return fused_paged_decode_kernel(
            q, cos_row, sin_row, k_pages, v_pages, block_tables,
            lengths, scale=scale, ks_pages=ks_pages, vs_pages=vs_pages)
    return fused_paged_decode_reference(
        q, cos_row, sin_row, k_pages, v_pages, block_tables, lengths,
        scale=scale, ks_pages=ks_pages, vs_pages=vs_pages)


# --------- flash chunk attention (prefill chunk + spec verify) ---------

def _chunk_softmax_step(q, k, v, kstart, o_ref, acc, m_sc, l_sc, *,
                        scale, block_k, rep, qoff, seq_len,
                        k_scale=None, v_scale=None, anc=None):
    """Online-softmax step for MULTI-TOKEN queries against one
    (block_k, D) cache block: query row r (= t*rep + h_rep) attends to
    columns ``kstart <= col <= qoff + t`` — the exact masks of
    ``generate._attn_with_cache`` with per-row ``kstart`` (ragged
    right-aligned context) and causal chunk positions. ``k/v_scale``:
    per-row int8 dequant scalars (dequant in VMEM). ``anc`` (ISSUE
    20): per-NODE ancestor bitmasks for TREE verify — a python list of
    T scalar int32s (SMEM reads), bit j of ``anc[t]`` set iff chunk
    node j lies on node t's root path; the intra-chunk causal triangle
    is replaced by the ancestor bit (committed columns below ``qoff``
    stay fully visible), everything else — kstart, online softmax,
    dequant — is byte-for-byte the linear path."""
    ki = pl.program_id(1)
    last = pl.num_programs(1) - 1

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_sc[...] = jnp.full_like(m_sc, -jnp.inf)
        l_sc[...] = jnp.zeros_like(l_sc)

    kk, vv = k, v
    if k_scale is not None:
        kk = (kk.astype(jnp.float32) * k_scale).astype(q.dtype)
    if v_scale is not None:
        vv = (vv.astype(jnp.float32) * v_scale).astype(q.dtype)
    # zero possibly-garbage cache rows: 0 * NaN would poison p @ v
    vrows = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, vv.shape, 0)
    vv = jnp.where(vrows < seq_len, vv, jnp.zeros_like(vv))
    s = jax.lax.dot_general(
        q, kk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # (T*rep, bk)
    cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if anc is None:
        qpos = qoff + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0) // rep
        ok = (cols <= qpos) & (cols >= kstart)
    else:
        # tree verify: select each query row's ancestor bitmask (T is
        # small and static — an unrolled select chain, no gather), then
        # allow committed columns plus chunk columns whose bit is set
        T = len(anc)
        rowt = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep
        av = jnp.zeros(s.shape, jnp.int32)
        for t in range(T):
            av = jnp.where(rowt == t, anc[t], av)
        rel = cols - qoff                    # chunk-node column index
        bit = (av >> jnp.clip(rel, 0, 31)) & 1
        ok = (cols < qoff) | ((rel < T) & (bit == 1))
        ok = ok & (cols >= kstart)
    s = jnp.where(ok, s, _fa.DEFAULT_MASK_VALUE)
    m_prev = m_sc[...]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    p = jnp.where(ok, p, 0.0)
    l_sc[...] = alpha * l_sc[...] + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), m_prev.shape)
    acc[...] = acc[...] * alpha[:, :1] + jax.lax.dot_general(
        p.astype(vv.dtype), vv, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_sc[...] = m_new

    @pl.when(ki == last)
    def _done():
        l = l_sc[:, :1]
        o_ref[0] = (acc[...] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def _chunk_kernel(q_ref, k_ref, v_ref, kst_ref, o_ref, acc, m_sc, l_sc,
                  *, scale, block_k, rep, qoff, seq_len):
    _chunk_softmax_step(q_ref[0], k_ref[0], v_ref[0],
                        kst_ref[pl.program_id(0)],
                        o_ref, acc, m_sc, l_sc, scale=scale,
                        block_k=block_k, rep=rep, qoff=qoff,
                        seq_len=seq_len)


def _chunk_kernel_rowq(q_ref, k_ref, v_ref, sk_ref, sv_ref, kst_ref,
                       o_ref, acc, m_sc, l_sc, *, scale, block_k, rep,
                       qoff, seq_len):
    """int8 temp-cache variant: per-row dequant scales ride (block_k, 1)
    VMEM blocks and broadcast over D — the dequanted fp copy of the
    gathered context never reaches HBM."""
    _chunk_softmax_step(q_ref[0], k_ref[0], v_ref[0],
                        kst_ref[pl.program_id(0)],
                        o_ref, acc, m_sc, l_sc, scale=scale,
                        block_k=block_k, rep=rep, qoff=qoff,
                        seq_len=seq_len, k_scale=sk_ref[0],
                        v_scale=sv_ref[0])


def _chunk_kernel_tree(q_ref, k_ref, v_ref, kst_ref, anc_ref, o_ref,
                       acc, m_sc, l_sc, *, scale, block_k, rep, qoff,
                       seq_len, nnodes):
    i = pl.program_id(0)
    _chunk_softmax_step(q_ref[0], k_ref[0], v_ref[0], kst_ref[i],
                        o_ref, acc, m_sc, l_sc, scale=scale,
                        block_k=block_k, rep=rep, qoff=qoff,
                        seq_len=seq_len,
                        anc=[anc_ref[i, t] for t in range(nnodes)])


def _chunk_kernel_rowq_tree(q_ref, k_ref, v_ref, sk_ref, sv_ref,
                            kst_ref, anc_ref, o_ref, acc, m_sc, l_sc,
                            *, scale, block_k, rep, qoff, seq_len,
                            nnodes):
    i = pl.program_id(0)
    _chunk_softmax_step(q_ref[0], k_ref[0], v_ref[0], kst_ref[i],
                        o_ref, acc, m_sc, l_sc, scale=scale,
                        block_k=block_k, rep=rep, qoff=qoff,
                        seq_len=seq_len, k_scale=sk_ref[0],
                        v_scale=sv_ref[0],
                        anc=[anc_ref[i, t] for t in range(nnodes)])


def flash_chunk_attention_reference(q, ck, cv, length, kstart, *,
                                    scale=None, k_rows=None,
                                    v_rows=None, tree_mask=None):
    """Pure-lax reference — op-for-op the jnp composition of
    ``generate._attn_with_cache`` (same einsums, f32 accumulation,
    -1e30 masks, dequant-then-cast), so the CPU fallback is
    BIT-identical to the unfused path. ``tree_mask`` (ISSUE 20):
    optional (B, T, T) ancestor-or-self matrix replacing the
    intra-chunk causal triangle for TREE verify (committed columns
    below the chunk stay fully visible; a chain tree reproduces the
    causal mask exactly)."""
    B, T, H, D = q.shape
    if (k_rows is None) != (v_rows is None):
        raise ValueError(
            "flash_chunk_attention: k_rows and v_rows must be passed "
            "together — int8 caches quantize both K and V")
    if k_rows is not None:
        ck = (ck.astype(jnp.float32) * k_rows[..., None]).astype(q.dtype)
        cv = (cv.astype(jnp.float32) * v_rows[..., None]).astype(q.dtype)
    nkv = ck.shape[2]
    if nkv != H:
        ck = jnp.repeat(ck, H // nkv, axis=2)
        cv = jnp.repeat(cv, H // nkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   ck.astype(jnp.float32))
    s = s * scale if scale is not None else s / math.sqrt(D)
    kpos = lax.broadcasted_iota(jnp.int32, s.shape, 3)
    if tree_mask is None:
        qpos = (length - T) + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(kpos <= qpos, s, -1e30)
    else:
        Smax = ck.shape[1]
        allow = jnp.concatenate(
            [jnp.ones((B, T, Smax - T), bool),
             jnp.asarray(tree_mask, bool)], axis=2)
        s = jnp.where(allow[:, None], s, -1e30)
    s = jnp.where(kpos >= jnp.asarray(kstart, jnp.int32)
                  [:, None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(cv.dtype), cv)


def flash_chunk_attention_kernel(q, ck, cv, length, kstart, *,
                                 scale=None, k_rows=None, v_rows=None,
                                 block_k: int = 512, tree_mask=None):
    """Pallas flash attention for the multi-token serving programs.

    q:       (B, T, H, D) rotated chunk queries
    ck/cv:   (B, W, HK, D) gathered right-aligned temp cache (int8 with
             ``k_rows``/``v_rows`` (B, W, HK) per-row dequant scales)
    length:  STATIC total width (``ctx_cap + T`` — the serving chunk
             and verify programs always pass their static window)
    kstart:  (B,) traced first valid cache column per row
    returns (B, T, H, D); query row t sees columns
    ``[kstart_b, ctx_cap + t]`` — exactly the unfused masks.

    tree_mask (ISSUE 20): optional (B, T, T) bool ancestor-or-self
    matrix — the chunk lanes become token-TREE nodes and node t sees
    chunk column j only when the matrix row allows it. The matrix
    packs into per-node int32 BITMASKS riding SMEM next to ``kstart``
    (hence T <= 32 in tree mode — comb trees are shallow and narrow),
    and only the mask predicate changes inside the step.
    """
    if not _PALLAS_OK:
        raise RuntimeError(
            "flash_chunk_attention_kernel: jax.experimental.pallas is "
            "unavailable — use flash_chunk_attention() for the "
            "pure-lax fallback")
    B, T, H, D = q.shape
    W, HK = ck.shape[1], ck.shape[2]
    assert H % HK == 0
    rep = H // HK
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    length = int(length)
    qoff = length - T
    bk = min(block_k, W)
    if (k_rows is None) != (v_rows is None):
        raise ValueError(
            "flash_chunk_attention: k_rows and v_rows must be passed "
            "together — int8 caches quantize both K and V")
    quant = k_rows is not None
    if tree_mask is not None and T > 32:
        raise ValueError(
            f"flash_chunk_attention: tree mode packs ancestor rows "
            f"into int32 bitmasks, so the tree is capped at 32 nodes "
            f"(got T={T})")

    # (B, T, H, D) -> (B*HK, T*rep, D): one grid row per kv-head group
    qt = q.reshape(B, T, HK, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B * HK, T * rep, D)
    kt = ck.transpose(0, 2, 1, 3).reshape(B * HK, W, D)
    vt = cv.transpose(0, 2, 1, 3).reshape(B * HK, W, D)
    kst = jnp.repeat(jnp.broadcast_to(
        jnp.asarray(kstart, jnp.int32), (B,)), HK)

    in_specs = [
        pl.BlockSpec((1, T * rep, D), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((1, bk, D), lambda i, j: (i, j, 0)),
        pl.BlockSpec((1, bk, D), lambda i, j: (i, j, 0)),
    ]
    inputs = [qt, kt, vt]
    tkw = {}
    if tree_mask is not None:
        tkw = {"nnodes": T}
        kernel_plain, kernel_quant = _chunk_kernel_tree, \
            _chunk_kernel_rowq_tree
    else:
        kernel_plain, kernel_quant = _chunk_kernel, _chunk_kernel_rowq
    if quant:
        def rows(sc):   # (B, W, HK) -> (B*HK, W, 1)
            return jnp.asarray(sc, jnp.float32).transpose(
                0, 2, 1).reshape(B * HK, W, 1)
        in_specs += [pl.BlockSpec((1, bk, 1), lambda i, j: (i, j, 0)),
                     pl.BlockSpec((1, bk, 1), lambda i, j: (i, j, 0))]
        inputs += [rows(k_rows), rows(v_rows)]
        kernel = functools.partial(kernel_quant, scale=s,
                                   block_k=bk, rep=rep, qoff=qoff,
                                   seq_len=length, **tkw)
    else:
        kernel = functools.partial(kernel_plain, scale=s, block_k=bk,
                                   rep=rep, qoff=qoff, seq_len=length,
                                   **tkw)
    in_specs.append(pl.BlockSpec(
        (B * HK,), lambda i, j: (0,),
        memory_space=pltpu.SMEM if _PALLAS_OK else None))
    inputs.append(kst)
    if tree_mask is not None:
        # per-node ancestor bitmask, repeated over kv-head groups like
        # kstart: bit j of anc[b*HK + g, t] = node j on node t's path
        bits = (jnp.asarray(tree_mask, jnp.int32)
                * (1 << jnp.arange(T, dtype=jnp.int32))[None, None, :]
                ).sum(axis=2)                             # (B, T)
        in_specs.append(pl.BlockSpec(
            (B * HK, T), lambda i, j: (0, 0),
            memory_space=pltpu.SMEM if _PALLAS_OK else None))
        inputs.append(jnp.repeat(bits, HK, axis=0))

    out = pl.pallas_call(
        kernel,
        grid=(B * HK, pl.cdiv(W, bk)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, T * rep, D), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * HK, T * rep, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((T * rep, D), jnp.float32),
            pltpu.VMEM((T * rep, 128), jnp.float32),
            pltpu.VMEM((T * rep, 128), jnp.float32),
        ],
        interpret=_fa._interpret_mode(),
    )(*inputs)
    return out.reshape(B, HK, T, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, T, H, D)


def flash_chunk_attention(q, ck, cv, length, kstart, *, scale=None,
                          k_rows=None, v_rows=None, use_kernel=None,
                          tree_mask=None):
    """Dispatcher for the multi-token serving attention: Pallas flash
    kernel on real TPU or when forced (interpret mode in tests),
    pure-lax reference — bit-identical to the unfused
    ``_attn_with_cache`` composition — elsewhere. Consumers:
    ``paged_prefill_chunk`` (the fused PREFILL kernel) and
    ``paged_verify_forward`` (the fused VERIFY kernel, linear AND —
    via ``tree_mask`` — tree speculative)."""
    if use_kernel is None:
        try:
            use_kernel = jax.devices()[0].platform == "tpu"
        except Exception:
            use_kernel = False
    if use_kernel:
        return flash_chunk_attention_kernel(
            q, ck, cv, length, kstart, scale=scale, k_rows=k_rows,
            v_rows=v_rows, tree_mask=tree_mask)
    return flash_chunk_attention_reference(
        q, ck, cv, length, kstart, scale=scale, k_rows=k_rows,
        v_rows=v_rows, tree_mask=tree_mask)
