"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor, to_tensor
from .._core import dtype as dtypes
from ._registry import register, as_tensor, raw


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else (default or dtypes.get_default_dtype())


@register("zeros", tensor_method=False)
def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)), _internal=True)


@register("ones", tensor_method=False)
def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)), _internal=True)


@register("full", tensor_method=False)
def full(shape, fill_value, dtype=None, name=None):
    fv = raw(fill_value)
    if dtype is None:
        return Tensor(jnp.full(_shape(shape), fv), _internal=True)
    return Tensor(jnp.full(_shape(shape), fv, _dt(dtype)), _internal=True)


@register("zeros_like")
def zeros_like(x, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.zeros_like(raw(as_tensor(x)), dtype=d), _internal=True)


@register("ones_like")
def ones_like(x, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.ones_like(raw(as_tensor(x)), dtype=d), _internal=True)


@register("full_like")
def full_like(x, fill_value, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype)
    return Tensor(jnp.full_like(raw(as_tensor(x)), raw(fill_value), dtype=d),
                  _internal=True)


@register("empty", tensor_method=False)
def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


@register("empty_like")
def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


@register("arange", tensor_method=False)
def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = raw(start), raw(end), raw(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (np.int64 if jnp.result_type(start, end, step) in
                 (jnp.int32, jnp.int64) else dtypes.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)),
                  _internal=True)


@register("linspace", tensor_method=False)
def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(raw(start), raw(stop), int(raw(num)),
                               dtype=_dt(dtype)), _internal=True)


@register("logspace", tensor_method=False)
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(raw(start), raw(stop), int(raw(num)),
                               base=raw(base), dtype=_dt(dtype)),
                  _internal=True)


@register("eye", tensor_method=False)
def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)),
                  _internal=True)


@register("diag", tensor_method=False)
def diag(x, offset=0, padding_value=0, name=None):
    def f(v):
        out = jnp.diag(v, k=offset)
        if v.ndim == 1 and padding_value != 0:
            mask = jnp.eye(out.shape[0], dtype=bool) if offset == 0 else \
                jnp.diag(jnp.ones(v.shape[0], dtype=bool), k=offset)
            out = jnp.where(mask, out, padding_value)
        return out
    return apply(f, as_tensor(x), name="diag")


@register("diagflat", tensor_method=False)
def diagflat(x, offset=0, name=None):
    return apply(lambda v: jnp.diagflat(v, k=offset), as_tensor(x),
                 name="diagflat")


@register("diag_embed", tensor_method=False)
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(v):
        n = v.shape[-1] + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + (-offset if offset < 0 else 0)
        c = idx + (offset if offset > 0 else 0)
        out = out.at[..., r, c].set(v)
        src = list(range(out.ndim))
        d1 = dim1 % out.ndim
        d2 = dim2 % out.ndim
        perm = [d for d in src if d not in (out.ndim - 2, out.ndim - 1)]
        res = [None] * out.ndim
        res[d1] = out.ndim - 2
        res[d2] = out.ndim - 1
        it = iter(perm)
        for i in range(out.ndim):
            if res[i] is None:
                res[i] = next(it)
        return jnp.transpose(out, res) if (d1, d2) != (out.ndim - 2,
                                                       out.ndim - 1) else out
    return apply(f, as_tensor(input), name="diag_embed")


@register("tril", tensor_method=True)
def tril(x, diagonal=0, name=None):
    return apply(lambda v: jnp.tril(v, k=diagonal), as_tensor(x), name="tril")


@register("triu", tensor_method=True)
def triu(x, diagonal=0, name=None):
    return apply(lambda v: jnp.triu(v, k=diagonal), as_tensor(x), name="triu")


@register("tril_indices", tensor_method=False)
def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(_dt(dtype))),
                  _internal=True)


@register("triu_indices", tensor_method=False)
def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(_dt(dtype))),
                  _internal=True)


@register("meshgrid", tensor_method=False)
def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    outs = apply(lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")),
                 *[as_tensor(a) for a in args], name="meshgrid")
    return list(outs)


@register("assign", tensor_method=False)
def assign(x, output=None, name=None):
    src = as_tensor(x) if not isinstance(x, (list, tuple, np.ndarray, int,
                                             float)) else Tensor(np.asarray(x))
    out = apply(lambda v: v + 0 if jnp.issubdtype(jnp.result_type(v),
                                                  jnp.inexact) else v,
                src, name="assign")
    if output is not None:
        output._inplace_from(out)
        return output
    return out


@register("clone")
def clone(x, name=None):
    return as_tensor(x).clone()


@register("complex", tensor_method=False)
def complex(real, imag, name=None):
    return apply(jax.lax.complex, as_tensor(real), as_tensor(imag),
                 name="complex")


@register("polar", tensor_method=False)
def polar(abs, angle, name=None):
    return apply(lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)),
                 as_tensor(abs), as_tensor(angle), name="polar")


@register("cast", tensor_method=False)
def cast(x, dtype, name=None):
    """reference: tensor/manipulation.py cast — functional dtype cast
    (the Tensor.cast method's standalone form)."""
    return as_tensor(x).cast(dtype)
