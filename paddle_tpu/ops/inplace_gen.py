"""Auto-generated in-place (`op_`) variants.

The reference maps every inplace op onto its functional kernel
(paddle/phi/ops/yaml inplace entries); here each `op_` calls the
functional op and rebinds the tensor's value via ``_inplace_from`` — the
framework's in-place emulation on immutable jax arrays (SURVEY §7 hard
part 1).
"""
from __future__ import annotations

from .._core.tensor import Tensor
from ._registry import as_tensor

# functional base -> generated <base>_ names. Bases resolve against the
# top-level paddle_tpu namespace after all op modules are loaded.
INPLACE_BASES = [
    "abs", "acos", "add", "addmm", "asin", "atan", "bernoulli",
    "bitwise_and", "bitwise_invert", "bitwise_left_shift",
    "bitwise_not", "bitwise_or", "bitwise_right_shift", "bitwise_xor",
    "cast", "ceil", "clip", "copysign", "cos", "cosh", "cumprod",
    "cumsum", "digamma", "divide", "equal", "erf", "exp", "expm1",
    "fill_diagonal", "fill_diagonal_tensor", "flatten", "floor",
    "floor_divide", "floor_mod",
    "frac", "gcd", "greater_equal", "greater_than", "hypot", "i0",
    "lcm", "ldexp", "lerp", "less_equal", "less_than", "lgamma", "log",
    "log10", "log1p", "log2", "logical_and", "logical_not",
    "logical_or", "logical_xor", "logit", "masked_fill", "multiply",
    "nan_to_num", "neg", "not_equal", "pow", "put_along_axis",
    "reciprocal", "remainder", "renorm", "round", "rsqrt", "scale",
    "scatter", "sigmoid", "sin", "sinh", "sqrt", "square", "squeeze",
    "subtract", "t", "tan", "tanh", "transpose", "tril", "triu",
    "trunc", "unsqueeze", "where", "multigammaln", "polygamma",
    "gammainc", "gammaincc", "gammaln", "sinc", "mod", "less",
    "masked_scatter", "index_fill",
]


def install(ns: dict):
    """Generate `<op>_` into namespace ns for every base present."""
    made = []
    for base in INPLACE_BASES:
        fn = ns.get(base)
        if fn is None or (base + "_") in ns:
            continue

        def make(f):
            def inplace(x, *args, **kwargs):
                t = as_tensor(x)
                out = f(t, *args, **kwargs)
                return t._inplace_from(out)
            return inplace

        ip = make(fn)
        ip.__name__ = base + "_"
        ip.__doc__ = f"In-place variant of :func:`{base}` (rebinds the " \
                     f"tensor's value)."
        ns[base + "_"] = ip
        # also attach as Tensor method when not already defined
        if not hasattr(Tensor, base + "_"):
            setattr(Tensor, base + "_", ip)
        made.append(base + "_")
    return made
