"""Linear algebra ops (reference: python/paddle/tensor/linalg.py,
kernels paddle/phi/kernels/{matmul,svd,qr,cholesky,...}_kernel.*).

Matmuls are the MXU path: they lower straight to XLA dot_general, with
precision controlled by FLAGS_tpu_matmul_precision.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from .._core.flags import flag_value
from ._registry import register, as_tensor, raw


def _precision():
    p = flag_value("tpu_matmul_precision")
    return None if p == "default" else p


@register("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b, precision=_precision())
    return apply(f, as_tensor(x), as_tensor(y), name="matmul")


@register("mm")
def mm(input, mat2, name=None):
    return matmul(input, mat2)


@register("bmm")
def bmm(x, y, name=None):
    return apply(lambda a, b: jnp.matmul(a, b, precision=_precision()),
                 as_tensor(x), as_tensor(y), name="bmm")


@register("dot")
def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), as_tensor(x),
                 as_tensor(y), name="dot")


@register("mv")
def mv(x, vec, name=None):
    return apply(lambda a, b: jnp.matmul(a, b, precision=_precision()),
                 as_tensor(x), as_tensor(vec), name="mv")


@register("addmm", tensor_method=False)
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i +
                 alpha * jnp.matmul(a, b, precision=_precision()),
                 as_tensor(input), as_tensor(x), as_tensor(y), name="addmm")


@register("einsum", tensor_method=False)
def einsum(equation, *operands, name=None):
    ts = [as_tensor(o) for o in operands]
    return apply(lambda *vs: jnp.einsum(equation, *vs, precision=_precision()),
                 *ts, name="einsum")


@register("norm", tensor_method=False)
def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    if p is None:
        p = 2 if axis is not None or x.ndim == 1 else "fro"
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def f(v):
        if p == "fro":
            return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(v, compute_uv=False), axis=-1,
                           keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=ax,
                                 keepdims=keepdim), 1.0 / p)
    return apply(f, x, name="norm")


vector_norm = norm


@register("matrix_norm", tensor_method=False)
def matrix_norm(x, p="fro", axis=[-2, -1], keepdim=False, name=None):
    return apply(lambda v: jnp.linalg.norm(v, ord=p, axis=tuple(axis),
                                           keepdims=keepdim),
                 as_tensor(x), name="matrix_norm")


@register("dist", tensor_method=False)
def dist(x, y, p=2, name=None):
    return norm(as_tensor(x) - as_tensor(y), p=float(p))


@register("t")
def t(input, name=None):
    return apply(lambda v: v.T if v.ndim == 2 else v, as_tensor(input),
                 name="t")


@register("transpose_matmul", tensor_method=False)
def transpose_matmul(x, y):
    return matmul(x, y, transpose_x=True)


@register("cross", tensor_method=False)
def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis if axis != 9 else next(
            i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply(f, as_tensor(x), as_tensor(y), name="cross")


@register("histogram", tensor_method=False)
def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    xv = np.asarray(raw(as_tensor(input)))
    lo, hi = (min, max) if (min != 0 or max != 0) else (xv.min(), xv.max())
    h, _ = np.histogram(xv, bins=bins, range=(lo, hi),
                        weights=None if weight is None else
                        np.asarray(raw(as_tensor(weight))), density=density)
    return Tensor(jnp.asarray(h if density else h.astype(np.int32)))


@register("dot_general", tensor_method=False)
def dot_general(lhs, rhs, dimension_numbers, name=None):
    """TPU-native extra: direct XLA dot_general access (no reference analog;
    the MXU primitive underlying all matmul ops)."""
    return apply(lambda a, b: jax.lax.dot_general(
        a, b, dimension_numbers, precision=_precision()),
        as_tensor(lhs), as_tensor(rhs), name="dot_general")


# ---- decompositions / solvers (CPU-offloaded where XLA-TPU lacks them) ----
def _linalg_op(name, jfn, n_out=1, tensor_method=False):
    def op(x, *args, name=None, **kwargs):
        res = apply(lambda v: jfn(v, *args, **kwargs), as_tensor(x), name=name)
        return res
    op.__name__ = name
    register(name, tensor_method)(op)
    return op


cholesky = _linalg_op("cholesky", lambda v, upper=False:
                      jnp.linalg.cholesky(v) if not upper
                      else jnp.swapaxes(jnp.linalg.cholesky(
                          jnp.swapaxes(v, -1, -2).conj()), -1, -2).conj())
inverse = _linalg_op("inverse", jnp.linalg.inv)
matrix_power = _linalg_op("matrix_power", jnp.linalg.matrix_power)
pinv = _linalg_op("pinv", jnp.linalg.pinv)


@register("det", tensor_method=False)
def det(x, name=None):
    return apply(jnp.linalg.det, as_tensor(x), name="det")


@register("slogdet", tensor_method=False)
def slogdet(x, name=None):
    outs = apply(lambda v: tuple(jnp.linalg.slogdet(v)), as_tensor(x),
                 name="slogdet")
    return outs


@register("svd", tensor_method=False)
def svd(x, full_matrices=False, name=None):
    return apply(lambda v: tuple(jnp.linalg.svd(
        v, full_matrices=full_matrices)), as_tensor(x), name="svd")


@register("qr", tensor_method=False)
def qr(x, mode="reduced", name=None):
    return apply(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), as_tensor(x),
                 name="qr")


@register("eig", tensor_method=False)
def eig(x, name=None):
    xv = np.asarray(raw(as_tensor(x)))
    w, v = np.linalg.eig(xv)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


@register("eigh", tensor_method=False)
def eigh(x, UPLO="L", name=None):
    return apply(lambda v: tuple(jnp.linalg.eigh(v,
                                                 symmetrize_input=False)),
                 as_tensor(x), name="eigh")


@register("eigvals", tensor_method=False)
def eigvals(x, name=None):
    xv = np.asarray(raw(as_tensor(x)))
    return Tensor(jnp.asarray(np.linalg.eigvals(xv)))


@register("eigvalsh", tensor_method=False)
def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda v: jnp.linalg.eigvalsh(v), as_tensor(x),
                 name="eigvalsh")


@register("solve", tensor_method=False)
def solve(x, y, name=None):
    return apply(jnp.linalg.solve, as_tensor(x), as_tensor(y), name="solve")


@register("triangular_solve", tensor_method=False)
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return apply(lambda a, b: jax.scipy.linalg.solve_triangular(
        a, b, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular), as_tensor(x), as_tensor(y),
        name="triangular_solve")


@register("cholesky_solve", tensor_method=False)
def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        z = jax.scipy.linalg.solve_triangular(L, b, lower=not upper,
                                              trans=1 if upper else 0)
        return jax.scipy.linalg.solve_triangular(L, z, lower=not upper,
                                                 trans=0 if upper else 1)
    return apply(f, as_tensor(x), as_tensor(y), name="cholesky_solve")


@register("lstsq", tensor_method=False)
def lstsq(x, y, rcond=None, driver=None, name=None):
    xv = np.asarray(raw(as_tensor(x)))
    yv = np.asarray(raw(as_tensor(y)))
    sol, res, rank, sv = np.linalg.lstsq(xv, yv, rcond=rcond)
    return (Tensor(jnp.asarray(sol)), Tensor(jnp.asarray(res)),
            Tensor(np.asarray(rank)), Tensor(jnp.asarray(sv)))


@register("matrix_rank", tensor_method=False)
def matrix_rank(x, tol=None, hermitian=False, atol=None, rtol=None,
                name=None):
    """reference: linalg.py matrix_rank + matrix_rank_atol_rtol op —
    rank = #singular values > max(atol, rtol * sigma_max); `tol` is the
    legacy absolute form."""
    def f(v):
        if hermitian:
            s = jnp.abs(jnp.linalg.eigvalsh(v))
        else:
            s = jnp.linalg.svd(v, compute_uv=False)
        smax = jnp.max(s, axis=-1, keepdims=True)
        if atol is not None or rtol is not None:
            thr = jnp.maximum(
                jnp.asarray(0.0 if atol is None else atol, s.dtype),
                (0.0 if rtol is None else rtol) * smax)
        elif tol is not None:
            thr = jnp.asarray(tol, s.dtype)
        else:
            eps = jnp.finfo(s.dtype).eps
            thr = smax * max(v.shape[-2], v.shape[-1]) * eps
        return jnp.sum(s > thr, axis=-1).astype(jnp.int32)
    return apply(f, as_tensor(x), name="matrix_rank")


@register("lu", tensor_method=False)
def lu(x, pivot=True, get_infos=False, name=None):
    xv = np.asarray(raw(as_tensor(x)))
    import scipy.linalg as sla
    lu_mat, piv = sla.lu_factor(xv)
    outs = (Tensor(jnp.asarray(lu_mat)),
            Tensor(jnp.asarray((piv + 1).astype(np.int32))))
    if get_infos:
        return outs + (Tensor(np.zeros(1, np.int32)),)
    return outs


@register("corrcoef", tensor_method=False)
def corrcoef(x, rowvar=True, name=None):
    return apply(lambda v: jnp.corrcoef(v, rowvar=rowvar), as_tensor(x),
                 name="corrcoef")


@register("cov", tensor_method=False)
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda v: jnp.cov(v, rowvar=rowvar,
                                   ddof=1 if ddof else 0), as_tensor(x),
                 name="cov")


@register("lu_unpack", tensor_method=False)
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu output into (P, L, U) (reference:
    paddle/phi/kernels/lu_unpack_kernel.h). Batched inputs unpack
    batch-wise; disabled outputs return None (3-tuple always)."""
    lu_mat = np.asarray(raw(as_tensor(x)))
    piv = np.asarray(raw(as_tensor(y))).astype(np.int64)
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = np.tril(lu_mat, -1)[..., :, :k].copy()
        idx = np.arange(k)
        L[..., idx, idx] = 1.0
        U = np.triu(lu_mat)[..., :k, :]
    if unpack_pivots:
        batch = lu_mat.shape[:-2]
        piv2 = piv.reshape((-1, piv.shape[-1]))
        Ps = np.zeros((piv2.shape[0], m, m), lu_mat.dtype)
        for b in range(piv2.shape[0]):
            perm = np.arange(m)
            for i, p in enumerate(piv2[b][:k]):
                perm[i], perm[p - 1] = perm[p - 1], perm[i]
            Ps[b][perm, np.arange(m)] = 1.0
        P = Ps.reshape(batch + (m, m))
    wrap = lambda v: None if v is None else Tensor(jnp.asarray(v),
                                                   _internal=True)
    return wrap(P), wrap(L), wrap(U)


from .parity import multi_dot  # noqa: E402,F401  (paddle.linalg.multi_dot)
