"""Elementwise math + reductions (reference: python/paddle/tensor/math.py,
kernels paddle/phi/kernels/*{activation,elementwise,reduce}*)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import jax.scipy.special as jsp

from .._core.autograd import apply
from .._core.tensor import Tensor
from .._core import dtype as dtypes
from ._registry import register, as_tensor, unary, binary, raw

# ---- unary elementwise ----
exp = unary("exp", jnp.exp)
expm1 = unary("expm1", jnp.expm1)
log = unary("log", jnp.log)
log2 = unary("log2", jnp.log2)
log10 = unary("log10", jnp.log10)
log1p = unary("log1p", jnp.log1p)
sqrt = unary("sqrt", jnp.sqrt)
rsqrt = unary("rsqrt", lambda x: jax.lax.rsqrt(x))
square = unary("square", jnp.square)
abs = unary("abs", jnp.abs)
absolute = abs
ceil = unary("ceil", jnp.ceil)
floor = unary("floor", jnp.floor)
round = unary("round", jnp.round)
trunc = unary("trunc", jnp.trunc)
frac = unary("frac", lambda x: x - jnp.trunc(x))
sign = unary("sign", jnp.sign)
sin = unary("sin", jnp.sin)
cos = unary("cos", jnp.cos)
tan = unary("tan", jnp.tan)
asin = unary("asin", jnp.arcsin)
acos = unary("acos", jnp.arccos)
atan = unary("atan", jnp.arctan)
sinh = unary("sinh", jnp.sinh)
cosh = unary("cosh", jnp.cosh)
tanh = unary("tanh", jnp.tanh)
asinh = unary("asinh", jnp.arcsinh)
acosh = unary("acosh", jnp.arccosh)
atanh = unary("atanh", jnp.arctanh)
reciprocal = unary("reciprocal", lambda x: 1.0 / x)
neg = unary("neg", jnp.negative)
negative = neg
erf = unary("erf", jax.lax.erf)
erfinv = unary("erfinv", jax.lax.erf_inv)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
lgamma = unary("lgamma", jsp.gammaln)
digamma = unary("digamma", jsp.digamma)
i0 = unary("i0", jsp.i0)
i0e = unary("i0e", jsp.i0e)
i1 = unary("i1", jsp.i1)
i1e = unary("i1e", jsp.i1e)
angle = unary("angle", jnp.angle)
conj = unary("conj", jnp.conj)
real = unary("real", jnp.real)
imag = unary("imag", jnp.imag)
rad2deg = unary("rad2deg", jnp.rad2deg)
deg2rad = unary("deg2rad", jnp.deg2rad)
logit = unary("logit", jsp.logit)
isnan = unary("isnan", jnp.isnan, inplace_variant=False)
isinf = unary("isinf", jnp.isinf, inplace_variant=False)
isfinite = unary("isfinite", jnp.isfinite, inplace_variant=False)

# ---- binary elementwise ----
add = binary("add", jnp.add)
subtract = binary("subtract", jnp.subtract)
multiply = binary("multiply", jnp.multiply)
divide = binary("divide", jnp.true_divide)
floor_divide = binary("floor_divide", jnp.floor_divide)
remainder = binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = binary("pow", jnp.power)
maximum = binary("maximum", jnp.maximum)
minimum = binary("minimum", jnp.minimum)
fmax = binary("fmax", jnp.fmax)
fmin = binary("fmin", jnp.fmin)
atan2 = binary("atan2", jnp.arctan2)
hypot = binary("hypot", jnp.hypot)
logaddexp = binary("logaddexp", jnp.logaddexp)
heaviside = binary("heaviside", jnp.heaviside)
gcd = binary("gcd", jnp.gcd)
lcm = binary("lcm", jnp.lcm)
nextafter = binary("nextafter", jnp.nextafter)
copysign = binary("copysign", jnp.copysign)
ldexp = binary("ldexp", jnp.ldexp)
inner = binary("inner", jnp.inner)
outer = binary("outer", jnp.outer)
kron = binary("kron", jnp.kron)


@register("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = raw(scale), raw(bias)
    if bias_after_scale:
        return apply(lambda v: v * s + b, as_tensor(x), name="scale")
    return apply(lambda v: (v + b) * s, as_tensor(x), name="scale")


@register("clip")
def clip(x, min=None, max=None, name=None):
    mn, mx = raw(min), raw(max)
    return apply(lambda v: jnp.clip(v, mn, mx), as_tensor(x), name="clip")


@register("lerp")
def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply(lambda a, b, w: a + w * (b - a), as_tensor(x),
                     as_tensor(y), weight, name="lerp")
    return apply(lambda a, b: a + weight * (b - a), as_tensor(x),
                 as_tensor(y), name="lerp")


@register("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply(lambda v: scale_b * jnp.tanh(scale_a * v), as_tensor(x),
                 name="stanh")


@register("multiplex")
def multiplex(inputs, index, name=None):
    def f(idx, *xs):
        return jnp.stack(xs, 0)[jnp.squeeze(idx, -1),
                                jnp.arange(xs[0].shape[0])]
    return apply(f, as_tensor(index),
                 *[as_tensor(i) for i in inputs], name="multiplex")


@register("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf,
                                          neginf=neginf),
                 as_tensor(x), name="nan_to_num")


# ---- reductions ----
def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _reduce(op_name, jfn):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _norm_axis(axis)
        d = dtypes.convert_dtype(dtype) if dtype is not None else None

        def f(v):
            out = jfn(v, axis=ax, keepdims=keepdim)
            return out.astype(d) if d is not None else out
        return apply(f, as_tensor(x), name=op_name)
    op.__name__ = op_name
    register(op_name)(op)
    return op


sum = _reduce("sum", jnp.sum)
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)


@register("max")
def max(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.max(v, axis=_norm_axis(axis), keepdims=keepdim),
                 as_tensor(x), name="max")


@register("min")
def min(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.min(v, axis=_norm_axis(axis), keepdims=keepdim),
                 as_tensor(x), name="min")


@register("amax")
def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


@register("amin")
def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


@register("logsumexp")
def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jsp.logsumexp(v, axis=_norm_axis(axis),
                                         keepdims=keepdim),
                 as_tensor(x), name="logsumexp")


@register("all")
def all(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.all(v, axis=_norm_axis(axis), keepdims=keepdim),
                 as_tensor(x), name="all")


@register("any")
def any(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.any(v, axis=_norm_axis(axis), keepdims=keepdim),
                 as_tensor(x), name="any")


@register("count_nonzero")
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply(lambda v: jnp.count_nonzero(v, axis=_norm_axis(axis),
                                             keepdims=keepdim).astype(jnp.int32),
                 as_tensor(x), name="count_nonzero")


# ---- cumulative ----
@register("cumsum")
def cumsum(x, axis=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None

    def f(v):
        vv = v.reshape(-1) if axis is None else v
        out = jnp.cumsum(vv, axis=0 if axis is None else int(axis))
        return out.astype(d) if d else out
    return apply(f, as_tensor(x), name="cumsum")


@register("cumprod")
def cumprod(x, dim=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None

    def f(v):
        out = jnp.cumprod(v, axis=int(dim))
        return out.astype(d) if d else out
    return apply(f, as_tensor(x), name="cumprod")


def _cum_extremum(x, axis, cmp, name):
    """Shared cummax/cummin: associative scan carrying (value, index) pairs;
    ties keep the later index (reference: paddle/phi/kernels/cum_maxmin_*)."""
    def f(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        idx = jnp.broadcast_to(
            jnp.arange(vv.shape[ax], dtype=jnp.int32).reshape(
                (-1,) + (1,) * (vv.ndim - ax - 1)), vv.shape)

        def combine(a, b):
            av, ai = a
            bv, bi = b
            take_b = cmp(bv, av)
            return (jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai))
        vals, inds = jax.lax.associative_scan(combine, (vv, idx), axis=ax)
        return vals, inds
    return apply(f, as_tensor(x), name=name)


@register("cummax", tensor_method=False)
def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extremum(x, axis, lambda b, a: b >= a, "cummax")


@register("cummin", tensor_method=False)
def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extremum(x, axis, lambda b, a: b <= a, "cummin")


@register("logcumsumexp")
def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else int(axis)
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=ax)
    return apply(f, as_tensor(x), name="logcumsumexp")


@register("diff", tensor_method=False)
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [as_tensor(x)]
    pre = app = None
    if prepend is not None:
        pre = as_tensor(prepend)
        args.append(pre)
    if append is not None:
        app = as_tensor(append)
        args.append(app)

    def f(v, *rest):
        i = 0
        p = a = None
        if pre is not None:
            p = rest[i]; i += 1
        if app is not None:
            a = rest[i]
        return jnp.diff(v, n=n, axis=axis, prepend=p, append=a)
    return apply(f, *args, name="diff")


@register("trace")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                     axis2=axis2), as_tensor(x), name="trace")


@register("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1,
                                        axis2=axis2),
                 as_tensor(x), name="diagonal")


@register("increment", tensor_method=False)
def increment(x, value=1.0, name=None):
    out = apply(lambda v: v + value, x, name="increment")
    return x._inplace_from(out)


@register("accuracy", tensor_method=False)
def accuracy(input, label, k=1, correct=None, total=None, name=None):
    def f(pred, lab):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        hit = (topk == lab.reshape(-1, 1)).any(axis=-1)
        return hit.mean(dtype=jnp.float32)
    return apply(f, as_tensor(input), as_tensor(label), name="accuracy")
