"""Op registration helpers.

TPU-native replacement for the reference's YAML-driven codegen
(reference: paddle/phi/ops/yaml/ops.yaml — 472 ops; generated C++ API via
paddle/phi/api/generator/api_gen.py). On TPU there is no kernel-dispatch
layer to generate: every op is its jnp/lax primitive composition, traced by
XLA. What we keep from the reference's discipline is a single registry so the
Tensor method surface is attached uniformly (the reference's monkey-patch in
python/paddle/tensor/__init__.py).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from .._core.autograd import apply
from .._core.tensor import Tensor

OPS: Dict[str, Callable] = {}
TENSOR_METHODS: Dict[str, Callable] = {}


def register(name: str, tensor_method: bool = True, method_name: str = None):
    def deco(fn):
        OPS[name] = fn
        if tensor_method:
            TENSOR_METHODS[method_name or name] = fn
        return fn
    return deco


def as_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    return Tensor(x, dtype=dtype)


def raw(x):
    return x._value if isinstance(x, Tensor) else x


def unary(name: str, jfn, tensor_method=True, inplace_variant=True):
    """Create + register a differentiable unary op from a jnp function."""
    def op(x, name=None):
        return apply(jfn, as_tensor(x), name=name)
    op.__name__ = name
    register(name, tensor_method)(op)
    if inplace_variant and tensor_method:
        def op_(self, name=None):
            return self._inplace_from(op(self))
        op_.__name__ = name + "_"
        TENSOR_METHODS[name + "_"] = op_
    return op


def binary(name: str, jfn, tensor_method=True):
    def op(x, y, name=None):
        return apply(jfn, as_tensor(x), as_tensor(y), name=name)
    op.__name__ = name
    register(name, tensor_method)(op)
    return op


def attach_tensor_methods():
    """Attach every registered op as a Tensor method (reference pattern:
    python/paddle/tensor/__init__.py tensor method attach list)."""
    for mname, fn in TENSOR_METHODS.items():
        if mname.endswith("_") and hasattr(Tensor, mname):
            continue
        if not hasattr(Tensor, mname):
            setattr(Tensor, mname, fn)
