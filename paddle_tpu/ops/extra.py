"""Long-tail op surface (reference: python/paddle/tensor/{math,
manipulation,creation,linalg,logic}.py — the remaining __all__ entries).

Mechanical jnp compositions; in-place ``op_`` variants are generated from
their functional bases at the bottom (reference pattern: inplace ops share
kernels with out-of-place, paddle/phi/ops/yaml inplace maps).
"""
from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp
from jax.scipy.special import (gammaln as _gammaln, digamma as _digamma,
                               gammainc as _gammainc,
                               gammaincc as _gammaincc)

from .._core.autograd import apply
from .._core.tensor import Tensor
from ._registry import as_tensor, raw

inf = float("inf")
nan = float("nan")
newaxis = None

__all__ = [
    "inf", "nan", "newaxis", "hstack", "vstack", "dstack", "column_stack",
    "row_stack", "hsplit", "vsplit", "dsplit", "atleast_1d", "atleast_2d",
    "atleast_3d", "unbind", "unflatten", "view_as", "reverse", "block_diag",
    "cartesian_prod", "combinations", "sinc", "signbit", "positive", "i0",
    "gammaln", "sgn", "isneginf", "isposinf", "isin", "gammainc",
    "gammaincc", "multigammaln", "polygamma", "copysign", "hypot", "ldexp",
    "frexp", "frac", "bitwise_invert", "bitwise_left_shift",
    "bitwise_right_shift", "less", "reduce_as", "trapezoid",
    "cumulative_trapezoid", "histogram_bin_edges", "vander", "tensordot",
    "cdist", "pdist", "matrix_transpose", "renorm", "slice_scatter",
    "select_scatter", "diagonal_scatter", "masked_fill", "masked_scatter",
    "index_fill", "take", "as_complex", "as_real", "is_complex",
    "is_integer", "is_floating_point", "standard_gamma", "log_normal",
    "shard_index", "add_n", "rank", "tolist", "set_printoptions",
    "disable_signal_handler", "check_shape", "flops", "LazyGuard",
]


def _un(fn, name):
    def op(x, *a, **k):
        k.pop("name", None)
        return apply(lambda v: fn(v, *a, **k), as_tensor(x), name=name)
    op.__name__ = name
    return op


# ---- stacking / splitting ----
def _multi(fn, name):
    def op(xs, name_=None):
        ts = [as_tensor(t) for t in xs]
        return apply(lambda *vs: fn(vs), *ts, name=name)
    op.__name__ = name
    return op


hstack = _multi(jnp.hstack, "hstack")
vstack = _multi(jnp.vstack, "vstack")
dstack = _multi(jnp.dstack, "dstack")
column_stack = _multi(jnp.column_stack, "column_stack")
row_stack = vstack


def _np_split(x, num_or_indices, axis, name):
    # split inside the traced function (multi-output apply) so gradients
    # flow to the input — wrapping precomputed parts as captured constants
    # would record a zero vjp
    x = as_tensor(x)
    if not isinstance(num_or_indices, int):
        num_or_indices = [int(raw(i)) for i in num_or_indices]
    return list(apply(
        lambda v: tuple(jnp.split(v, num_or_indices, axis=axis)),
        x, name=name))


def hsplit(x, num_or_indices, name=None):
    x = as_tensor(x)
    return _np_split(x, num_or_indices, 0 if x.ndim == 1 else 1, "hsplit")


def vsplit(x, num_or_indices, name=None):
    return _np_split(x, num_or_indices, 0, "vsplit")


def dsplit(x, num_or_indices, name=None):
    return _np_split(x, num_or_indices, 2, "dsplit")


def atleast_1d(*xs, name=None):
    out = [apply(jnp.atleast_1d, as_tensor(x), name="atleast_1d")
           for x in xs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*xs, name=None):
    out = [apply(jnp.atleast_2d, as_tensor(x), name="atleast_2d")
           for x in xs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*xs, name=None):
    out = [apply(jnp.atleast_3d, as_tensor(x), name="atleast_3d")
           for x in xs]
    return out[0] if len(out) == 1 else out


def unbind(x, axis=0):
    x = as_tensor(x)
    n = x.shape[axis]
    return [apply(lambda v, i=i: jnp.take(v, i, axis=axis), x,
                  name="unbind") for i in range(n)]


def unflatten(x, axis, shape, name=None):
    x = as_tensor(x)

    def f(v):
        ax = axis % v.ndim
        new = list(v.shape[:ax]) + list(shape) + list(v.shape[ax + 1:])
        return v.reshape(new)
    return apply(f, x, name="unflatten")


def view_as(x, other, name=None):
    return apply(lambda v, o: v.reshape(o.shape), as_tensor(x),
                 as_tensor(other), name="view_as")


def reverse(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return _un(lambda v: jnp.flip(v, ax), "reverse")(x)


def block_diag(inputs, name=None):
    ts = [as_tensor(t) for t in inputs]

    def f(*vs):
        vs = [jnp.atleast_2d(v) for v in vs]
        R = sum(v.shape[0] for v in vs)
        C = sum(v.shape[1] for v in vs)
        out = jnp.zeros((R, C), vs[0].dtype)
        r = c = 0
        for v in vs:
            out = jax.lax.dynamic_update_slice(out, v.astype(out.dtype),
                                               (r, c))
            r += v.shape[0]
            c += v.shape[1]
        return out
    return apply(f, *ts, name="block_diag")


def cartesian_prod(x, name=None):
    ts = [as_tensor(t) for t in x]

    def f(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.ravel() for g in grids], axis=-1)
    return apply(f, *ts, name="cartesian_prod")


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    x = as_tensor(x)
    n = x.shape[0]
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), np.int32).reshape(-1, r)
    return apply(lambda v: v[jnp.asarray(idx)], x, name="combinations")


# ---- elementwise / special ----
sinc = _un(jnp.sinc, "sinc")
signbit = _un(jnp.signbit, "signbit")
positive = _un(jnp.positive, "positive")
i0 = _un(lambda v: jax.scipy.special.i0(v), "i0")
gammaln = _un(_gammaln, "gammaln")
digamma_fn = _digamma


def sgn(x, name=None):
    def f(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)
    return apply(f, as_tensor(x), name="sgn")


def isneginf(x, name=None):
    return _un(jnp.isneginf, "isneginf")(x)


def isposinf(x, name=None):
    return _un(jnp.isposinf, "isposinf")(x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply(lambda v, t: jnp.isin(v, t, invert=invert), as_tensor(x),
                 as_tensor(test_x), name="isin")


def gammainc(x, y, name=None):
    return apply(_gammainc, as_tensor(x), as_tensor(y), name="gammainc")


def gammaincc(x, y, name=None):
    return apply(_gammaincc, as_tensor(x), as_tensor(y), name="gammaincc")


def multigammaln(x, p, name=None):
    def f(v):
        c = 0.25 * p * (p - 1) * _math.log(_math.pi)
        return c + sum(_gammaln(v - 0.5 * i) for i in range(p))
    return apply(f, as_tensor(x), name="multigammaln")


def polygamma(x, n, name=None):
    if n == 0:
        return apply(_digamma, as_tensor(x), name="polygamma")

    def f(v):
        base = lambda s: _digamma(s)
        for _ in range(n):
            base = jax.grad(base)
        return jax.vmap(base)(v.reshape(-1).astype(jnp.float32)).reshape(
            v.shape)
    return apply(f, as_tensor(x), name="polygamma")


def copysign(x, y, name=None):
    return apply(jnp.copysign, as_tensor(x), as_tensor(y), name="copysign")


def hypot(x, y, name=None):
    return apply(jnp.hypot, as_tensor(x), as_tensor(y), name="hypot")


def ldexp(x, y, name=None):
    return apply(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)),
                 as_tensor(x), as_tensor(y), name="ldexp")


def frexp(x, name=None):
    return apply(lambda v: jnp.frexp(v), as_tensor(x), name="frexp",
                 multi_out=True)


def frac(x, name=None):
    return _un(lambda v: v - jnp.trunc(v), "frac")(x)


def bitwise_invert(x, name=None):
    return _un(jnp.invert, "bitwise_invert")(x)


def bitwise_left_shift(x, y, name=None):
    return apply(jnp.left_shift, as_tensor(x), as_tensor(y),
                 name="bitwise_left_shift")


def bitwise_right_shift(x, y, name=None):
    return apply(jnp.right_shift, as_tensor(x), as_tensor(y),
                 name="bitwise_right_shift")


def less(x, y, name=None):
    return as_tensor(x) < y


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (reference: reduce_as op)."""
    x, target = as_tensor(x), as_tensor(target)

    def f(v, t):
        extra = v.ndim - t.ndim
        v = jnp.sum(v, axis=tuple(range(extra))) if extra else v
        axes = tuple(i for i in range(v.ndim)
                     if t.shape[i] == 1 and v.shape[i] != 1)
        return jnp.sum(v, axis=axes, keepdims=True) if axes else v
    return apply(f, x, target, name="reduce_as")


# ---- reductions / integration ----
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = as_tensor(y)
    if x is not None:
        return apply(lambda yv, xv: jax.scipy.integrate.trapezoid(
            yv, xv, axis=axis), y, as_tensor(x), name="trapezoid")
    return apply(lambda yv: jax.scipy.integrate.trapezoid(
        yv, dx=dx or 1.0, axis=axis), y, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = as_tensor(y)

    def f(yv, *rest):
        ax = axis % yv.ndim
        y1 = jax.lax.slice_in_dim(yv, 1, yv.shape[ax], axis=ax)
        y0 = jax.lax.slice_in_dim(yv, 0, yv.shape[ax] - 1, axis=ax)
        if rest:
            xv = rest[0]
            x1 = jax.lax.slice_in_dim(xv, 1, xv.shape[ax], axis=ax)
            x0 = jax.lax.slice_in_dim(xv, 0, xv.shape[ax] - 1, axis=ax)
            d = x1 - x0
        else:
            d = dx or 1.0
        return jnp.cumsum((y0 + y1) * d / 2.0, axis=ax)
    if x is not None:
        return apply(f, y, as_tensor(x), name="cumulative_trapezoid")
    return apply(f, y, name="cumulative_trapezoid")


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    x = as_tensor(x)

    def f(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else \
            (jnp.min(v), jnp.max(v))
        return jnp.linspace(lo, hi, bins + 1).astype(jnp.float32)
    return apply(f, x, name="histogram_bin_edges")


def vander(x, n=None, increasing=False, name=None):
    return _un(lambda v: jnp.vander(v, n, increasing=increasing),
               "vander")(x)


# ---- linalg-ish ----
def tensordot(x, y, axes=2, name=None):
    return apply(lambda a, b: jnp.tensordot(a, b, axes=axes), as_tensor(x),
                 as_tensor(y), name="tensordot")


def cdist(x, y, p=2.0, compute_mode=None, name=None):
    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
        return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
    return apply(f, as_tensor(x), as_tensor(y), name="cdist")


def pdist(x, p=2.0, name=None):
    x = as_tensor(x)
    n = x.shape[0]
    iu = np.triu_indices(n, k=1)

    def f(v):
        d = v[:, None, :] - v[None, :, :]
        if p == 2.0:
            m = jnp.sqrt(jnp.sum(d * d, -1) + 1e-30)
        else:
            m = jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
        return m[iu]
    return apply(f, x, name="pdist")


def matrix_transpose(x, name=None):
    return _un(lambda v: jnp.swapaxes(v, -1, -2), "matrix_transpose")(x)


def renorm(x, p, axis, max_norm, name=None):
    def f(v):
        ax = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=ax, keepdims=True) ** (1 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return _un(f, "renorm")(x)


# ---- scatter-style ----
def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    x, value = as_tensor(x), as_tensor(value)

    def f(v, val):
        idx = [slice(None)] * v.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sd)
        return v.at[tuple(idx)].set(val.astype(v.dtype))
    return apply(f, x, value, name="slice_scatter")


def select_scatter(x, value, axis, index, name=None):
    x, value = as_tensor(x), as_tensor(value)

    def f(v, val):
        idx = [slice(None)] * v.ndim
        idx[axis] = index
        return v.at[tuple(idx)].set(val.astype(v.dtype))
    return apply(f, x, value, name="select_scatter")


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    x, y = as_tensor(x), as_tensor(y)

    def f(v, val):
        # build index grids for the diagonal
        n = min(v.shape[axis1], v.shape[axis2]) - abs(offset)
        i = jnp.arange(n) + max(0, -offset)
        j = jnp.arange(n) + max(0, offset)
        idx = [slice(None)] * v.ndim
        idx[axis1] = i
        idx[axis2] = j
        return v.at[tuple(idx)].set(val.astype(v.dtype))
    return apply(f, x, y, name="diagonal_scatter")


def masked_fill(x, mask, value, name=None):
    # single canonical implementation (manipulation.py): Tensor values are
    # real op args, scalars cast to x's dtype
    from .manipulation import masked_fill as _mf
    return _mf(x, mask, value, name=name)


def masked_scatter(x, mask, value, name=None):
    x, mask, value = as_tensor(x), as_tensor(mask), as_tensor(value)

    def f(v, m, val):
        mflat = m.ravel()
        pos = jnp.cumsum(mflat) - 1
        src = jnp.take(val.ravel(), jnp.clip(pos, 0, val.size - 1))
        return jnp.where(mflat, src, v.ravel()).reshape(v.shape)
    return apply(f, x, mask, value, name="masked_scatter")


def index_fill(x, index, axis, value, name=None):
    x, index = as_tensor(x), as_tensor(index)

    def f(v, idx):
        moved = jnp.moveaxis(v, axis, 0)
        moved = moved.at[idx].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return apply(f, x, index, name="index_fill")


def take(x, index, mode="raise", name=None):
    x, index = as_tensor(x), as_tensor(index)
    md = {"raise": "clip"}.get(mode, mode)  # jit cannot raise; clamp
    return apply(lambda v, i: jnp.take(v.ravel(), i, mode=md), x, index,
                 name="take")


# ---- complex views ----
def as_complex(x, name=None):
    return _un(lambda v: jax.lax.complex(v[..., 0], v[..., 1]),
               "as_complex")(x)


def as_real(x, name=None):
    return _un(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
               "as_real")(x)


def is_complex(x) -> bool:
    return bool(jnp.issubdtype(as_tensor(x)._value.dtype,
                               jnp.complexfloating))


def is_integer(x) -> bool:
    return bool(jnp.issubdtype(as_tensor(x)._value.dtype, jnp.integer))


def is_floating_point(x) -> bool:
    return bool(jnp.issubdtype(as_tensor(x)._value.dtype, jnp.floating))


# ---- random ----
def standard_gamma(alpha, name=None):
    from .._core.random import next_rng_key
    alpha = as_tensor(alpha)
    key = next_rng_key()
    return Tensor(jax.random.gamma(key, alpha._value), _internal=True)


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    from .._core.random import next_rng_key
    key = next_rng_key()
    out = jnp.exp(mean + std * jax.random.normal(
        key, tuple(shape or [1]), jnp.float32))
    return Tensor(out, _internal=True)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """reference: tensor/manipulation.py shard_index (PS embedding shard
    remap)."""
    size = (index_num + nshards - 1) // nshards

    def f(v):
        shard = v // size
        local = v % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return _un(f, "shard_index")(input)


def add_n(inputs, name=None):
    ts = [as_tensor(t) for t in (inputs if isinstance(inputs, (list, tuple))
                                 else [inputs])]
    return apply(lambda *vs: sum(vs[1:], vs[0]), *ts, name="add_n")


# ---- misc framework-level ----
def rank(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x).ndim), _internal=True)


def tolist(x):
    return as_tensor(x).numpy().tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    pass


def check_shape(x):
    return list(as_tensor(x).shape)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """reference: hapi/dynamic_flops.py — rough conv/linear FLOP count."""
    from ..nn.layer.layers import Layer
    total = [0]
    from .. import nn

    def count(layer, inp, out):
        if isinstance(layer, nn.Linear):
            total[0] += 2 * int(np.prod(inp[0].shape)) * \
                layer.weight.shape[-1] // inp[0].shape[-1]
        elif hasattr(nn, "Conv2D") and isinstance(layer, nn.Conv2D):
            kh, kw = layer._kernel_size if isinstance(
                layer._kernel_size, (list, tuple)) else \
                (layer._kernel_size, layer._kernel_size)
            total[0] += 2 * int(np.prod(out.shape)) * \
                layer._in_channels * kh * kw

    hooks = []
    for _, sub in net.named_sublayers():
        hooks.append(sub.register_forward_post_hook(
            lambda l, i, o: count(l, i, o)))
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.zeros(input_size, np.float32))
    net(x)
    for h in hooks:
        h.remove()
    return total[0]


class LazyGuard:
    """reference: python/paddle/nn/initializer/lazy_init.py — deferred
    parameter initialization. Params here are cheap (host numpy), so the
    guard is a no-op context for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
