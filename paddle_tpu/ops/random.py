"""Random sampling ops (reference: python/paddle/tensor/random.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from .._core import dtype as dtypes
from .._core.random import next_rng_key
from ._registry import register, as_tensor, raw
from .creation import _shape, _dt


@register("rand", tensor_method=False)
def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


@register("uniform", tensor_method=False)
def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = next_rng_key() if seed == 0 else jax.random.key(seed)
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=float(raw(min)),
                                     maxval=float(raw(max))), _internal=True)


@register("randn", tensor_method=False)
def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_rng_key(), _shape(shape), _dt(dtype)),
                  _internal=True)


@register("normal", tensor_method=False)
def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = as_tensor(mean) if not np.isscalar(mean) else mean
        s = as_tensor(std) if not np.isscalar(std) else std
        mshape = (np.shape(raw(m)) if not np.isscalar(m) else
                  np.shape(raw(s)))
        key = next_rng_key()
        eps = jax.random.normal(key, mshape, dtypes.get_default_dtype())
        args = [t for t in (m, s) if isinstance(t, Tensor)]

        def f(*vs):
            i = 0
            mm = vs[i] if isinstance(m, Tensor) else m
            i += isinstance(m, Tensor)
            ss = vs[i] if isinstance(s, Tensor) else s
            return mm + ss * eps
        return apply(f, *args, name="normal")
    sh = _shape(shape) if shape is not None else ()
    return Tensor(mean + std * jax.random.normal(
        next_rng_key(), sh, dtypes.get_default_dtype()), _internal=True)


@register("standard_normal", tensor_method=False)
def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


@register("gaussian", tensor_method=False)
def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = next_rng_key() if seed == 0 else jax.random.key(seed)
    return Tensor(mean + std * jax.random.normal(key, _shape(shape),
                                                 _dt(dtype)), _internal=True)


@register("randint", tensor_method=False)
def randint(low=0, high=None, shape=[1], dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_rng_key(), _shape(shape),
                                     int(raw(low)), int(raw(high)),
                                     _dt(dtype, np.dtype("int64"))),
                  _internal=True)


@register("randint_like", tensor_method=False)
def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = as_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


@register("randperm", tensor_method=False)
def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_rng_key(), int(raw(n)))
                  .astype(_dt(dtype, np.dtype("int64"))), _internal=True)


@register("shuffle", tensor_method=False)
def shuffle(x, axis=0, name=None):
    perm = jax.random.permutation(next_rng_key(),
                                  as_tensor(x).shape[int(axis)])
    return apply(lambda v: jnp.take(v, perm, axis=int(axis)), as_tensor(x),
                 name="shuffle")


@register("multinomial", tensor_method=False)
def multinomial(x, num_samples=1, replacement=False, name=None):
    xv = raw(as_tensor(x))
    key = next_rng_key()
    logits = jnp.log(jnp.clip(xv, 1e-30, None))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(num_samples,) + xv.shape[:-1])
        out = jnp.moveaxis(out, 0, -1) if xv.ndim > 1 else out
    else:
        g = jax.random.gumbel(key, xv.shape)
        out = jnp.argsort(-(logits + g), axis=-1)[..., :num_samples]
    return Tensor(out.astype(jnp.int32), _internal=True)


@register("bernoulli", tensor_method=False)
def bernoulli(x, name=None):
    p = raw(as_tensor(x))
    return Tensor(jax.random.bernoulli(next_rng_key(), p).astype(
        jnp.result_type(p)), _internal=True)


@register("poisson", tensor_method=False)
def poisson(x, name=None):
    lam = raw(as_tensor(x))
    return Tensor(jax.random.poisson(next_rng_key(), lam).astype(
        jnp.result_type(lam)), _internal=True)


@register("binomial", tensor_method=False)
def binomial(count, prob, name=None):
    n = raw(as_tensor(count))
    p = raw(as_tensor(prob))
    return Tensor(jax.random.binomial(next_rng_key(), n, p).astype(jnp.int32),
                  _internal=True)


@register("exponential_", tensor_method=False)
def exponential_(x, lam=1.0, name=None):
    x = as_tensor(x)
    v = jax.random.exponential(next_rng_key(), tuple(x.shape),
                               x.dtype) / lam
    x._inplace_assign(v)
    return x


@register("normal_", tensor_method=False)
def normal_(x, mean=0.0, std=1.0, name=None):
    x = as_tensor(x)
    v = mean + std * jax.random.normal(next_rng_key(), tuple(x.shape), x.dtype)
    x._inplace_assign(v)
    return x


@register("uniform_", tensor_method=False)
def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x = as_tensor(x)
    key = next_rng_key() if seed == 0 else jax.random.key(seed)
    x._inplace_assign(jax.random.uniform(key, tuple(x.shape), x.dtype,
                                         minval=min, maxval=max))
    return x


@register("rand_like", tensor_method=False)
def rand_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return uniform(x.shape, dtype or x.dtype, 0.0, 1.0)


@register("randn_like", tensor_method=False)
def randn_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return randn(x.shape, dtype or x.dtype)


@register("cauchy_", tensor_method=False)
def cauchy_(x, loc=0, scale=1, name=None):
    """reference: tensor/random.py cauchy_ — in-place Cauchy fill."""
    x = as_tensor(x)
    v = jax.random.cauchy(next_rng_key(), tuple(x.shape), x.dtype)
    x._inplace_assign(loc + scale * v)
    return x


@register("geometric_", tensor_method=False)
def geometric_(x, probs, name=None):
    """reference: tensor/random.py geometric_ — in-place fill with
    log(u)/log1p(-p), the reference's continuous-support form (its docstring
    example includes values < 1; no ceil/clamp)."""
    x = as_tensor(x)
    u = jax.random.uniform(next_rng_key(), tuple(x.shape), jnp.float32,
                           minval=jnp.finfo(jnp.float32).tiny, maxval=1.0)
    v = jnp.log(u) / jnp.log1p(-jnp.float32(probs))
    x._inplace_assign(v.astype(x.dtype))
    return x


@register("log_normal_", tensor_method=False)
def log_normal_(x, mean=1.0, std=2.0, name=None):
    """reference: tensor/random.py log_normal_ — in-place exp(N(mean, std))."""
    x = as_tensor(x)
    v = jnp.exp(mean + std * jax.random.normal(next_rng_key(),
                                               tuple(x.shape), x.dtype))
    x._inplace_assign(v)
    return x
