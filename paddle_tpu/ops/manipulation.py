"""Shape/layout manipulation ops
(reference: python/paddle/tensor/manipulation.py; stride/view kernels
paddle/phi/kernels/stride/ — on TPU views are XLA copies that fuse away)."""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from .._core import dtype as dtypes
from ._registry import register, as_tensor, raw, TENSOR_METHODS


def _ishape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s)
                 for s in shape)


@register("reshape")
def reshape(x, shape, name=None):
    s = _ishape(shape)
    return apply(lambda v: jnp.reshape(v, s), as_tensor(x), name="reshape")


def reshape_(x, shape, name=None):
    return x._inplace_from(reshape(x, shape))


TENSOR_METHODS["reshape_"] = reshape_


@register("view")
def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    d = dtypes.convert_dtype(shape_or_dtype)
    return apply(lambda v: v.view(d) if hasattr(v, "view") else
                 jax.lax.bitcast_convert_type(v, d), as_tensor(x), name="view")


@register("flatten")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    so = stop_axis % nd if nd else 0

    def f(v):
        shape = v.shape[:sa] + (-1,) + v.shape[so + 1:]
        return jnp.reshape(v, shape)
    return apply(f, x, name="flatten")


@register("squeeze")
def squeeze(x, axis=None, name=None):
    x = as_tensor(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return apply(lambda v: jnp.squeeze(v, axis=ax), x, name="squeeze")


@register("unsqueeze")
def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._value) if isinstance(a, Tensor) else int(a) for a in axes]
    return apply(lambda v: jnp.expand_dims(v, axis=tuple(axes)), as_tensor(x),
                 name="unsqueeze")


for _n, _f in (("squeeze", squeeze), ("unsqueeze", unsqueeze)):
    def _mk(f):
        def op_(self, axis=None):
            return self._inplace_from(f(self, axis) if axis is not None
                                      else f(self))
        return op_
    TENSOR_METHODS[_n + "_"] = _mk(_f)


@register("transpose")
def transpose(x, perm=None, name=None):
    p = None if perm is None else tuple(int(i) for i in perm)
    return apply(lambda v: jnp.transpose(v, p), as_tensor(x), name="transpose")


@register("moveaxis")
def moveaxis(x, source, destination, name=None):
    return apply(lambda v: jnp.moveaxis(v, source, destination), as_tensor(x),
                 name="moveaxis")


@register("swapaxes")
def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda v: jnp.swapaxes(v, axis0, axis1), as_tensor(x),
                 name="swapaxes")


swapdims = swapaxes
TENSOR_METHODS["swapdims"] = swapaxes


@register("concat", tensor_method=False)
def concat(x, axis=0, name=None):
    axis = int(raw(axis))
    ts = [as_tensor(t) for t in x]
    return apply(lambda *vs: jnp.concatenate(vs, axis=axis), *ts, name="concat")


@register("stack", tensor_method=False)
def stack(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    return apply(lambda *vs: jnp.stack(vs, axis=axis), *ts, name="stack")


@register("unstack", tensor_method=False)
def unstack(x, axis=0, num=None, name=None):
    x = as_tensor(x)
    n = num if num is not None else x.shape[axis]
    outs = apply(lambda v: tuple(jnp.moveaxis(v, axis, 0)[i] for i in range(n)),
                 x, name="unstack")
    return list(outs)


@register("split", tensor_method=False)
def split(x, num_or_sections, axis=0, name=None):
    x = as_tensor(x)
    axis = int(raw(axis))
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {dim} is not divisible by "
                f"num={num_or_sections} (reference errors likewise)")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(raw(s)) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = dim - sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1])

    def f(v):
        return tuple(jax.lax.slice_in_dim(v, int(o), int(o + s), axis=axis)
                     for o, s in zip(offsets, sizes))
    return list(apply(f, x, name="split"))


@register("chunk", tensor_method=False)
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


@register("tensor_split", tensor_method=False)
def tensor_split(x, num_or_indices, axis=0, name=None):
    x = as_tensor(x)
    dim = x.shape[int(axis)]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, extra = divmod(dim, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
        return split(x, sizes, axis)
    idx = [0] + list(num_or_indices) + [dim]
    sizes = [idx[i + 1] - idx[i] for i in range(len(idx) - 1)]
    return split(x, sizes, axis)


@register("tile")
def tile(x, repeat_times, name=None):
    r = _ishape(repeat_times)
    return apply(lambda v: jnp.tile(v, r), as_tensor(x), name="tile")


@register("repeat_interleave")
def repeat_interleave(x, repeats, axis=None, name=None):
    rep = raw(repeats)
    return apply(lambda v: jnp.repeat(v, rep, axis=axis), as_tensor(x),
                 name="repeat_interleave")


@register("expand")
def expand(x, shape, name=None):
    x = as_tensor(x)
    s = list(_ishape(shape))
    xs = x.shape
    for i in range(1, len(xs) + 1):
        if s[-i] == -1:
            s[-i] = xs[-i]
    return apply(lambda v: jnp.broadcast_to(v, tuple(s)), x, name="expand")


@register("expand_as")
def expand_as(x, y, name=None):
    return expand(x, as_tensor(y).shape)


@register("broadcast_to")
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


@register("broadcast_tensors", tensor_method=False)
def broadcast_tensors(input, name=None):
    ts = [as_tensor(t) for t in input]
    outs = apply(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *ts,
                 name="broadcast_tensors")
    return list(outs)


@register("broadcast_shape", tensor_method=False)
def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@register("flip")
def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply(lambda v: jnp.flip(v, axis=ax), as_tensor(x), name="flip")


@register("rot90")
def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), as_tensor(x),
                 name="rot90")


@register("roll")
def roll(x, shifts, axis=None, name=None):
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else shifts
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda v: jnp.roll(v, sh, axis=ax), as_tensor(x), name="roll")


@register("gather", tensor_method=False)
def gather(x, index, axis=0, name=None):
    axis = int(raw(axis))
    # index is a real op arg (not a baked closure) so static-mode replay
    # and the tape see it — same for every indexed op below
    return apply(lambda v, idx: jnp.take(
        v, idx.reshape(-1) if idx.ndim > 1 else idx, axis=axis),
        as_tensor(x), as_tensor(index), name="gather")


@register("gather_nd", tensor_method=False)
def gather_nd(x, index, name=None):
    def f(v, idx):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply(f, as_tensor(x), as_tensor(index), name="gather_nd")


@register("take_along_axis", tensor_method=False)
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(lambda v, idx: jnp.take_along_axis(v, idx, axis=axis),
                 as_tensor(arr), as_tensor(indices),
                 name="take_along_axis")


@register("put_along_axis", tensor_method=False)
def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    arr = as_tensor(arr)
    vals = as_tensor(values) if not np.isscalar(values) else values

    def f(v, idx, *rest):
        val = rest[0] if rest else jnp.full_like(idx, values, dtype=v.dtype)
        val = jnp.broadcast_to(val, idx.shape) if hasattr(val, "shape") else val
        if reduce == "assign":
            mode = "set"
        elif reduce in ("add", "sum"):
            mode = "add"
        elif reduce in ("mul", "multiply"):
            mode = "multiply"
        elif reduce == "amax":
            mode = "max"
        elif reduce == "amin":
            mode = "min"
        else:
            raise ValueError(f"unsupported reduce {reduce}")
        # build open indices for all other axes
        ax = axis % v.ndim
        ii = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        full_idx = tuple(idx if d == ax else ii[d] for d in range(v.ndim))
        return getattr(v.at[full_idx], mode)(val)
    args = (arr, as_tensor(indices), vals) if isinstance(vals, Tensor) \
        else (arr, as_tensor(indices))
    return apply(f, *args, name="put_along_axis")


@register("scatter", tensor_method=False)
def scatter(x, index, updates, overwrite=True, name=None):
    def f(v, idx, u):
        if overwrite:
            return v.at[idx].set(u)
        return v.at[idx].add(u)
    return apply(f, as_tensor(x), as_tensor(index), as_tensor(updates),
                 name="scatter")


@register("scatter_nd_add", tensor_method=False)
def scatter_nd_add(x, index, updates, name=None):
    def f(v, idx, u):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return apply(f, as_tensor(x), as_tensor(index), as_tensor(updates),
                 name="scatter_nd_add")


@register("scatter_nd", tensor_method=False)
def scatter_nd(index, updates, shape, name=None):
    s = _ishape(shape)

    def f(idx, u):
        return jnp.zeros(s, u.dtype).at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)
    return apply(f, as_tensor(index), as_tensor(updates),
                 name="scatter_nd")


@register("index_select", tensor_method=False)
def index_select(x, index, axis=0, name=None):
    return apply(lambda v, idx: jnp.take(v, idx, axis=axis), as_tensor(x),
                 as_tensor(index), name="index_select")


@register("index_add", tensor_method=False)
def index_add(x, index, axis, value, name=None):
    def f(v, idx, u):
        vm = jnp.moveaxis(v, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        return jnp.moveaxis(vm.at[idx].add(um), 0, axis)
    return apply(f, as_tensor(x), as_tensor(index), as_tensor(value),
                 name="index_add")


@register("index_put", tensor_method=False)
def index_put(x, indices, value, accumulate=False, name=None):
    def f(v, u, *idx):
        return v.at[idx].add(u) if accumulate else v.at[idx].set(u)
    return apply(f, as_tensor(x), as_tensor(value),
                 *[as_tensor(i) for i in indices], name="index_put")


@register("masked_select", tensor_method=False)
def masked_select(x, mask, name=None):
    # dynamic-shape output: evaluated on host (not jittable), parity API
    xv = np.asarray(raw(as_tensor(x)))
    mv = np.asarray(raw(as_tensor(mask)))
    return Tensor(jnp.asarray(xv[np.broadcast_to(mv, xv.shape)]),
                  _internal=True)


@register("masked_fill", tensor_method=False)
def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return apply(lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
                     as_tensor(x), as_tensor(mask), value,
                     name="masked_fill")
    v = raw(value)
    return apply(lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a),
                 as_tensor(x), as_tensor(mask), name="masked_fill")


@register("where", tensor_method=False)
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        nz = np.nonzero(np.asarray(raw(as_tensor(condition))))
        return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int32)),
                      _internal=True)
    return apply(lambda c, a, b: jnp.where(c, a, b),
                 as_tensor(condition), as_tensor(x), as_tensor(y),
                 name="where")


@register("nonzero", tensor_method=False)
def nonzero(x, as_tuple=False, name=None):
    nz = np.nonzero(np.asarray(raw(as_tensor(x))))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int32))[:, None],
                            _internal=True) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int32)),
                  _internal=True)


@register("pad", tensor_method=False)
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = as_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(raw(p)) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pad applies to last len(pad)//2 spatial dims,
        # ordered (last_dim_lo, last_dim_hi, second_last_lo, ...)
        k = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NLC/NHWC/NDHWC
            dims = list(range(1, 1 + k))
        else:  # NCL/NCHW/NCDHW
            dims = list(range(nd - k, nd))
        for i, d in enumerate(reversed(dims)):
            width[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return apply(lambda v: jnp.pad(v, width, mode="constant",
                                       constant_values=value), x, name="pad")
    return apply(lambda v: jnp.pad(v, width, mode=jmode), x, name="pad")


@register("as_strided", tensor_method=False)
def as_strided(x, shape, stride, offset=0, name=None):
    def f(v):
        flat = v.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for dim, (s, st) in enumerate(zip(shape, stride)):
            r = np.arange(s) * st
            idx += r.reshape((-1,) + (1,) * (len(shape) - dim - 1))
        return flat[jnp.asarray(idx)]
    return apply(f, as_tensor(x), name="as_strided")


@register("unfold", tensor_method=False)
def unfold(x, axis, size, step, name=None):
    x = as_tensor(x)
    dim = x.shape[axis]
    n = (dim - size) // step + 1

    def f(v):
        vm = jnp.moveaxis(v, axis, 0)
        windows = jnp.stack([jax.lax.dynamic_slice_in_dim(vm, i * step, size, 0)
                             for i in range(n)], axis=0)
        # windows: (n, size, ...) -> move to (..., n, size) at position axis
        w = jnp.moveaxis(windows, (0, 1), (axis, v.ndim))
        return w
    return apply(f, x, name="unfold")


@register("unique", tensor_method=False)
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    xv = np.asarray(raw(as_tensor(x)))
    res = np.unique(xv, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        res = (res,)
    outs = [Tensor(jnp.asarray(r), _internal=True) for r in res]
    return outs[0] if len(outs) == 1 else tuple(outs)


@register("unique_consecutive", tensor_method=False)
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    xv = np.asarray(raw(as_tensor(x)))
    if axis is None:
        xv = xv.reshape(-1)
        keep = np.concatenate([[True], xv[1:] != xv[:-1]])
    else:
        d = np.any(np.diff(xv, axis=axis) != 0,
                   axis=tuple(i for i in range(xv.ndim) if i != axis))
        keep = np.concatenate([[True], d])
    vals = np.compress(keep, xv, axis=0 if axis is None else axis)
    outs = [Tensor(jnp.asarray(vals), _internal=True)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int32)), _internal=True))
    if return_counts:
        idx = np.nonzero(keep)[0]
        cnt = np.diff(np.concatenate([idx, [len(keep)]]))
        outs.append(Tensor(jnp.asarray(cnt.astype(np.int32)), _internal=True))
    return outs[0] if len(outs) == 1 else tuple(outs)


@register("one_hot", tensor_method=False)
def one_hot(x, num_classes, name=None):
    return apply(lambda idx: jax.nn.one_hot(
        idx, num_classes, dtype=dtypes.get_default_dtype()),
        as_tensor(x), name="one_hot")


@register("bincount", tensor_method=False)
def bincount(x, weights=None, minlength=0, name=None):
    xv = raw(as_tensor(x))
    w = raw(as_tensor(weights)) if weights is not None else None
    return Tensor(jnp.bincount(xv, weights=w, minlength=minlength),
                  _internal=True)


@register("numel", tensor_method=False)
def numel(x, name=None):
    return Tensor(np.asarray(as_tensor(x).size, dtype=np.int64),
                  _internal=False)


@register("shape", tensor_method=False)
def shape(input):
    return Tensor(np.asarray(as_tensor(input).shape, dtype=np.int64))


@register("slice", tensor_method=False)
def slice(input, axes, starts, ends, name=None):
    x = as_tensor(input)
    sl = [builtins.slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        st = int(raw(st)); en = int(raw(en))
        sl[ax] = builtins.slice(st, en)
    sl = tuple(sl)
    return apply(lambda v: v[sl], x, name="slice")


@register("strided_slice", tensor_method=False)
def strided_slice(x, axes, starts, ends, strides, name=None):
    x = as_tensor(x)
    sl = [builtins.slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        sl[ax] = builtins.slice(int(raw(st)), int(raw(en)), int(raw(sr)))
    sl = tuple(sl)
    return apply(lambda v: v[sl], x, name="strided_slice")


@register("crop", tensor_method=False)
def crop(x, shape=None, offsets=None, name=None):
    x = as_tensor(x)
    s = _ishape(shape)
    offs = [0] * x.ndim if offsets is None else [int(raw(o)) for o in offsets]
    s = [x.shape[i] - offs[i] if d == -1 else d for i, d in enumerate(s)]
    sl = tuple(builtins.slice(o, o + d) for o, d in zip(offs, s))
    return apply(lambda v: v[sl], x, name="crop")


@register("flatten_", tensor_method=False)
def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace_from(flatten(x, start_axis, stop_axis))


TENSOR_METHODS["flatten_"] = flatten_


@register("index_add_", tensor_method=False)
def index_add_(x, index, axis, value, name=None):
    """reference: manipulation.py index_add_ — in-place variant."""
    x = as_tensor(x)
    out = index_add(x, index, axis, value)
    x._inplace_assign(out._value, node=out._node, out_index=out._out_index)
    return x


@register("index_put_", tensor_method=False)
def index_put_(x, indices, value, accumulate=False, name=None):
    """reference: manipulation.py index_put_ — in-place variant."""
    x = as_tensor(x)
    out = index_put(x, indices, value, accumulate)
    x._inplace_assign(out._value, node=out._node, out_index=out._out_index)
    return x


@register("fill_diagonal")
def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """reference: tensor/manipulation.py fill_diagonal_ — write ``value``
    along the (offset) diagonal; ``wrap`` repeats the diagonal down tall
    matrices like the reference (numpy fill_diagonal wrap semantics)."""
    x = as_tensor(x)

    def f(v):
        n, m = v.shape[-2], v.shape[-1]
        i = jnp.arange(n)[:, None]
        j = jnp.arange(m)[None, :]
        d = j - i
        mask = d == offset
        if wrap and n > m:
            # repeat the diagonal block every (m+1) rows
            mask = (j - (i % (m + 1))) == offset
        return jnp.where(mask, jnp.asarray(value, v.dtype), v)
    return apply(f, x, name="fill_diagonal")


@register("fill_diagonal_tensor")
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """reference: tensor/manipulation.py fill_diagonal_tensor_ — write the
    rows of ``y`` along the (dim1, dim2) diagonal."""
    x, y = as_tensor(x), as_tensor(y)

    def f(v, w):
        vm = jnp.moveaxis(v, (dim1 % v.ndim, dim2 % v.ndim), (-2, -1))
        n, m = vm.shape[-2], vm.shape[-1]
        i = jnp.arange(n)[:, None]
        j = jnp.arange(m)[None, :]
        mask = (j - i) == offset
        # diagonal length and w broadcast to it along the last axis
        diag_len = int(np.sum(np.asarray((np.arange(m)[None, :] -
                                          np.arange(n)[:, None])
                                         == offset)))
        wf = jnp.broadcast_to(w, vm.shape[:-2] + (diag_len,))
        full = jnp.zeros_like(vm)
        rows = jnp.nonzero(np.asarray((np.arange(m)[None, :] -
                                       np.arange(n)[:, None]) == offset))
        full = full.at[..., rows[0], rows[1]].set(wf)
        out = jnp.where(mask, full, vm)
        return jnp.moveaxis(out, (-2, -1), (dim1 % v.ndim, dim2 % v.ndim))
    return apply(f, x, y, name="fill_diagonal_tensor")
