"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from ._registry import register, as_tensor, binary, unary, raw

equal = binary("equal", lambda a, b: a == b)
not_equal = binary("not_equal", lambda a, b: a != b)
less_than = binary("less_than", lambda a, b: a < b)
less_equal = binary("less_equal", lambda a, b: a <= b)
greater_than = binary("greater_than", lambda a, b: a > b)
greater_equal = binary("greater_equal", lambda a, b: a >= b)
logical_and = binary("logical_and", jnp.logical_and)
logical_or = binary("logical_or", jnp.logical_or)
logical_xor = binary("logical_xor", jnp.logical_xor)
logical_not = unary("logical_not", jnp.logical_not, inplace_variant=False)
bitwise_and = binary("bitwise_and", jnp.bitwise_and)
bitwise_or = binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = binary("bitwise_xor", jnp.bitwise_xor)
bitwise_not = unary("bitwise_not", jnp.bitwise_not, inplace_variant=False)
bitwise_left_shift = binary("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = binary("bitwise_right_shift", jnp.right_shift)


@register("allclose")
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=float(raw(rtol)),
                                           atol=float(raw(atol)),
                                           equal_nan=equal_nan),
                 as_tensor(x), as_tensor(y), name="allclose")


@register("isclose")
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=float(raw(rtol)),
                                          atol=float(raw(atol)),
                                          equal_nan=equal_nan),
                 as_tensor(x), as_tensor(y), name="isclose")


@register("equal_all")
def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), as_tensor(x),
                 as_tensor(y), name="equal_all")


@register("is_empty", tensor_method=False)
def is_empty(x, name=None):
    return Tensor(np.asarray(as_tensor(x).size == 0))


@register("is_tensor", tensor_method=False)
def is_tensor(x):
    return isinstance(x, Tensor)


@register("isreal", tensor_method=False)
def isreal(x, name=None):
    return apply(lambda v: jnp.isreal(v), as_tensor(x), name="isreal")
