"""Functional op surface (reference: python/paddle/tensor/*).

Importing this package registers every op and attaches the Tensor method
surface (the reference's monkey-patch pass in python/paddle/tensor/__init__.py).
"""
from . import _registry
from .math import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403

from .extra import *  # noqa: F401,F403
from .parity import *  # noqa: F401,F403

from . import math  # noqa: F401
from . import creation  # noqa: F401
from . import manipulation  # noqa: F401
from . import logic  # noqa: F401
from . import linalg  # noqa: F401
from . import search  # noqa: F401
from . import random  # noqa: F401
from . import extra  # noqa: F401
from . import parity  # noqa: F401

_registry.attach_tensor_methods()

from . import inplace_gen as _inplace_gen  # noqa: E402
_inplace_gen.install(globals())

OPS = _registry.OPS
