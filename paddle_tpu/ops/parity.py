"""Op-parity batch: long-tail ops surfaced by the ops.yaml coverage audit
(tools/op_coverage.py).

reference kernels: sequence_mask (paddle/phi/kernels/sequence_mask_kernel.h),
gather_tree (gather_tree_kernel.h — beam-search finalize), edit_distance
(edit_distance_kernel.cu), top_p_sampling (top_p_sampling_kernel.cu),
clip_by_norm (clip_by_norm_kernel.h), multi_dot (multi_dot_kernel.h),
lu_unpack (lu_unpack_kernel.h), uniform_/gaussian_ inplace
(uniform_inplace_kernel.cu / gaussian_inplace).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .._core.autograd import apply, no_grad
from .._core.tensor import Tensor
from ._registry import register, as_tensor, raw, TENSOR_METHODS

__all__ = [
    "sequence_mask", "gather_tree", "edit_distance", "top_p_sampling",
    "clip_by_norm", "multi_dot", "dequantize_log", "lookup_table_dequant",
]


@register("sequence_mask", tensor_method=False)
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: sequence_mask_kernel — mask[i, j] = j < x[i]. With an
    explicit maxlen the lengths are a real op arg (recorded/replayable);
    maxlen=None derives the static mask width from the data on the host."""
    from .._core import dtype as dtypes
    from .._core.autograd import apply
    d = dtypes.convert_dtype(dtype) if dtype is not None else jnp.int32
    if maxlen is not None and maxlen > 0:
        m = int(maxlen)

        def f(lv):
            return (lax.broadcasted_iota(jnp.int32, lv.shape + (m,),
                                         lv.ndim) < lv[..., None]).astype(d)
        return apply(f, as_tensor(x), name="sequence_mask")
    xv = raw(as_tensor(x))
    m = int(np.asarray(jax.device_get(xv)).max())
    out = (lax.broadcasted_iota(jnp.int32, xv.shape + (m,), xv.ndim)
           < xv[..., None]).astype(d)
    return Tensor(out, _internal=True)


@register("gather_tree", tensor_method=False)
def gather_tree(ids, parents, name=None):
    """Beam-search finalize: walk parent pointers from the last step back
    (reference: gather_tree_kernel). ids/parents: (max_time, batch, beam).
    """
    iv, pv = raw(as_tensor(ids)), raw(as_tensor(parents))
    T = iv.shape[0]

    def walk(carry, t):
        beam_idx = carry                      # (batch, beam) beam to follow
        tok = jnp.take_along_axis(iv[t], beam_idx, axis=1)
        parent = jnp.take_along_axis(pv[t], beam_idx, axis=1)
        return parent, tok

    beam0 = jnp.broadcast_to(jnp.arange(iv.shape[2]), iv.shape[1:])
    _, toks = lax.scan(walk, beam0, jnp.arange(T - 1, -1, -1))
    return Tensor(toks[::-1], _internal=True)


@register("edit_distance", tensor_method=False)
def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Batched Levenshtein distance (reference: edit_distance_kernel).
    Host-side DP (a metric, not a training op). Returns (distance (B, 1),
    sequence_num)."""
    a = np.asarray(jax.device_get(raw(as_tensor(input))))
    b = np.asarray(jax.device_get(raw(as_tensor(label))))
    il = np.asarray(jax.device_get(raw(as_tensor(input_length)))) \
        if input_length is not None else np.full(a.shape[0], a.shape[1])
    ll = np.asarray(jax.device_get(raw(as_tensor(label_length)))) \
        if label_length is not None else np.full(b.shape[0], b.shape[1])
    ignored = set(ignored_tokens or [])

    def clean(seq, n):
        return [t for t in seq[:int(n)] if t not in ignored]

    out = np.zeros((a.shape[0], 1), np.float32)
    for i in range(a.shape[0]):
        s, t = clean(a[i], il[i]), clean(b[i], ll[i])
        dp = np.arange(len(t) + 1, dtype=np.float32)
        for x in range(1, len(s) + 1):
            prev = dp.copy()
            dp[0] = x
            for y in range(1, len(t) + 1):
                dp[y] = min(prev[y] + 1, dp[y - 1] + 1,
                            prev[y - 1] + (s[x - 1] != t[y - 1]))
        d = dp[len(t)]
        if normalized:
            d = d / max(1, len(t))
        out[i, 0] = d
    return (Tensor(jnp.asarray(out), _internal=True),
            Tensor(jnp.asarray(np.int64(a.shape[0])), _internal=True))


@register("top_p_sampling", tensor_method=False)
def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling (reference: top_p_sampling_kernel). x: (B, V)
    probabilities; ps: (B,) cumulative-probability cutoffs. Returns
    (sampled probability, sampled id)."""
    xv = raw(as_tensor(x)).astype(jnp.float32)
    pv = jnp.broadcast_to(raw(as_tensor(ps)).astype(jnp.float32),
                          xv.shape[:1])
    from .._core.random import next_rng_key
    key = jax.random.key(seed) if seed is not None and seed >= 0 \
        else next_rng_key()
    order = jnp.argsort(-xv, axis=-1)
    sorted_p = jnp.take_along_axis(xv, order, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep tokens whose EXCLUSIVE prefix sum is below the cutoff (always
    # keeps the top token)
    keep = (cum - sorted_p) < pv[:, None]
    masked = jnp.where(keep, sorted_p, 0.0)
    masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
    pick = jax.random.categorical(key, jnp.log(
        jnp.where(masked > 0, masked, 1e-38)), axis=-1)
    ids = jnp.take_along_axis(order, pick[:, None], axis=-1)[:, 0]
    probs = jnp.take_along_axis(xv, ids[:, None], axis=-1)
    return (Tensor(probs, _internal=True),
            Tensor(ids[:, None].astype(jnp.int32), _internal=True))


@register("clip_by_norm")
def clip_by_norm(x, max_norm, name=None):
    """reference: clip_by_norm_kernel — x * max_norm / max(||x||, max_norm).
    """
    def fn(v):
        n = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
        scale = jnp.where(n > max_norm, max_norm / n, 1.0)
        return (v.astype(jnp.float32) * scale).astype(v.dtype)
    return apply(fn, as_tensor(x), name="clip_by_norm")


@register("multi_dot", tensor_method=False)
def multi_dot(x, name=None):
    """Matrix-chain product with optimal association order (reference:
    multi_dot_kernel; order DP identical to np.linalg.multi_dot).
    Differentiable: the chain folds through the framework's matmul."""
    mats = [as_tensor(m) for m in x]
    if len(mats) == 1:
        return mats[0]
    from .linalg import matmul
    dims = [mats[0].shape[0]] + [m.shape[-1] for m in mats]
    n = len(mats)
    cost = np.zeros((n, n))
    split = np.zeros((n, n), np.int32)
    for ln in range(2, n + 1):
        for i in range(n - ln + 1):
            j = i + ln - 1
            cost[i, j] = np.inf
            for k in range(i, j):
                c = (cost[i, k] + cost[k + 1, j] +
                     dims[i] * dims[k + 1] * dims[j + 1])
                if c < cost[i, j]:
                    cost[i, j] = c
                    split[i, j] = k

    def build(i, j):
        if i == j:
            return mats[i]
        k = split[i, j]
        return matmul(build(i, k), build(k + 1, j))
    return build(0, n - 1)


# ---- in-place random fills (reference: uniform_inplace / gaussian_inplace
# kernels; python Tensor.uniform_/normal_/exponential_) ----
def _uniform_(self, min=-1.0, max=1.0, seed=0, name=None):
    from .._core.random import next_rng_key
    key = jax.random.key(seed) if seed else next_rng_key()
    with no_grad():
        val = jax.random.uniform(key, tuple(self.shape),
                                 jnp.float32, min, max).astype(
            raw(self).dtype)
        self._inplace_assign(val)
    return self


def _normal_(self, mean=0.0, std=1.0, seed=0, name=None):
    from .._core.random import next_rng_key
    key = jax.random.key(seed) if seed else next_rng_key()
    with no_grad():
        val = (jax.random.normal(key, tuple(self.shape), jnp.float32)
               * std + mean).astype(raw(self).dtype)
        self._inplace_assign(val)
    return self


def _exponential_(self, lam=1.0, seed=0, name=None):
    from .._core.random import next_rng_key
    key = jax.random.key(seed) if seed else next_rng_key()
    with no_grad():
        val = (jax.random.exponential(key, tuple(self.shape), jnp.float32)
               / lam).astype(raw(self).dtype)
        self._inplace_assign(val)
    return self


TENSOR_METHODS["uniform_"] = _uniform_
TENSOR_METHODS["normal_"] = _normal_
TENSOR_METHODS["exponential_"] = _exponential_


@register("dequantize_log", tensor_method=False)
def dequantize_log(x, dict, name=None):
    """reference: phi/kernels/cpu/dequantize_log_kernel.cc — 8-bit
    log-quantized values decode through a 128-entry magnitude table;
    negative codes mirror to negative magnitudes."""
    d = raw(as_tensor(dict))

    def f(codes):
        c = codes.astype(jnp.int32)
        return jnp.where(c < 0, -jnp.take(d, c + 128), jnp.take(d, c))
    return apply(f, as_tensor(x), name="dequantize_log")


@register("lookup_table_dequant", tensor_method=False)
def lookup_table_dequant(w, ids, padding_idx=-1, name=None):
    """reference: phi/kernels/cpu/lookup_table_dequant_kernel.cc — an
    embedding lookup whose rows are stored 8-bit quantized: row layout is
    [min, max, packed uint8 payload in the remaining float32 columns];
    dequant = (max-min)/256 * byte + min. padding_idx rows come back 0."""
    def f(table, idx):
        rows = jnp.take(table, idx.astype(jnp.int32), axis=0)
        mn = rows[..., 0:1]
        mx = rows[..., 1:2]
        payload = jax.lax.bitcast_convert_type(rows[..., 2:], jnp.uint8)
        payload = payload.reshape(*rows.shape[:-1], -1)
        scale = (mx - mn) / 256.0
        out = scale * payload.astype(jnp.float32) + mn
        if padding_idx is not None and padding_idx >= 0:
            out = jnp.where((idx == padding_idx)[..., None], 0.0, out)
        return out
    return apply(f, as_tensor(w), as_tensor(ids),
                 name="lookup_table_dequant")
