"""Search / sort / statistics ops (reference: python/paddle/tensor/search.py,
python/paddle/tensor/stat.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from ._registry import register, as_tensor, raw


@register("argmax")
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmax(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(jnp.int32)
    return apply(f, as_tensor(x), name="argmax")


@register("argmin")
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmin(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(jnp.int32)
    return apply(f, as_tensor(x), name="argmin")


@register("argsort")
def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(v):
        idx = jnp.argsort(v, axis=axis, stable=True,
                          descending=descending)
        return idx.astype(jnp.int32)
    return apply(f, as_tensor(x), name="argsort")


@register("sort", tensor_method=False)
def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(v):
        out = jnp.sort(v, axis=axis, stable=True, descending=descending)
        return out
    return apply(f, as_tensor(x), name="sort")


@register("topk")
def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(raw(k))

    def f(v):
        ax = -1 if axis is None else int(axis)
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx.astype(jnp.int32), -1, ax))
    return apply(f, as_tensor(x), name="topk")


@register("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        sv = jnp.sort(v, axis=axis)
        si = jnp.argsort(v, axis=axis, stable=True)
        val = jnp.take(sv, k - 1, axis=axis)
        idx = jnp.take(si, k - 1, axis=axis).astype(jnp.int32)
        if keepdim:
            val = jnp.expand_dims(val, axis)
            idx = jnp.expand_dims(idx, axis)
        return val, idx
    return apply(f, as_tensor(x), name="kthvalue")


@register("mode", tensor_method=False)
def mode(x, axis=-1, keepdim=False, name=None):
    xv = np.asarray(raw(as_tensor(x)))
    import scipy.stats as st
    # always compute with keepdims so the broadcast against xv is valid,
    # then squeeze at the end (keepdim=False used to double-squeeze)
    vals = np.asarray(st.mode(xv, axis=axis, keepdims=True).mode)
    idx = np.apply_along_axis(
        lambda a: a.shape[0] - 1 - np.argmax(a[::-1]), axis, xv == vals)
    if keepdim:
        idx = np.expand_dims(idx, axis)
    else:
        vals = np.squeeze(vals, axis)
    return (Tensor(jnp.asarray(vals)),
            Tensor(jnp.asarray(idx.astype(jnp.int32))))


@register("median", tensor_method=False)
def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(v):
        if mode == "avg":
            return jnp.median(v, axis=axis, keepdims=keepdim)
        vv = jnp.sort(v.reshape(-1) if axis is None else v,
                      axis=0 if axis is None else axis)
        ax = 0 if axis is None else axis
        n = vv.shape[ax]
        return jnp.take(vv, (n - 1) // 2, axis=ax)
    return apply(f, as_tensor(x), name="median")


@register("nanmedian", tensor_method=False)
def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply(lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim),
                 as_tensor(x), name="nanmedian")


@register("quantile", tensor_method=False)
def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    qv = raw(as_tensor(q)) if not np.isscalar(q) else q
    return apply(lambda v: jnp.quantile(v, qv, axis=axis, keepdims=keepdim,
                                        method=interpolation),
                 as_tensor(x), name="quantile")


@register("nanquantile", tensor_method=False)
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    qv = raw(as_tensor(q)) if not np.isscalar(q) else q
    return apply(lambda v: jnp.nanquantile(v, qv, axis=axis, keepdims=keepdim,
                                           method=interpolation),
                 as_tensor(x), name="nanquantile")


@register("std", tensor_method=False)
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda v: jnp.std(v, axis=ax, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), as_tensor(x), name="std")


@register("var", tensor_method=False)
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda v: jnp.var(v, axis=ax, ddof=1 if unbiased else 0,
                                   keepdims=keepdim), as_tensor(x), name="var")


@register("searchsorted", tensor_method=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"

    def f(s, v):
        out = jnp.searchsorted(s, v, side=side) if s.ndim == 1 else \
            jax.vmap(lambda a, b: jnp.searchsorted(a, b, side=side))(
                s.reshape(-1, s.shape[-1]),
                v.reshape(-1, v.shape[-1])).reshape(v.shape)
        return out.astype(jnp.int32)
    return apply(f, as_tensor(sorted_sequence), as_tensor(values),
                 name="searchsorted")


@register("bucketize", tensor_method=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


@register("index_sample", tensor_method=False)
def index_sample(x, index, name=None):
    return apply(lambda v, idx: jnp.take_along_axis(v, idx, axis=1),
                 as_tensor(x), as_tensor(index), name="index_sample")


@register("histogramdd", tensor_method=False)
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xv = np.asarray(raw(as_tensor(x)))
    h, edges = np.histogramdd(xv, bins=bins, range=ranges, density=density,
                              weights=None if weights is None else
                              np.asarray(raw(as_tensor(weights))))
    return (Tensor(jnp.asarray(h)),
            [Tensor(jnp.asarray(e)) for e in edges])
